"""Persistent memory-mapped corpus store (the ``.npack`` cache).

Public surface:

  * ``resolve_store(arg)`` — the pipeline/CLI entry: a ``CorpusStore`` over
    the resolved cache root, or None when disabled (``off``/``NEMO_CORPUS_CACHE``).
  * ``CorpusStore.load_packed(dir)`` — warm path: mmap the store into a
    packed MollyOutput (appending new runs first when the directory grew);
    None on miss/stale/corruption, always loudly.
  * ``CorpusStore.put(dir, molly)`` — populate from either ingest producer
    (native packed-first or pure-Python object loader).

Format, fingerprinting, producers and shard IO live in ``npack.py``; the
mmap reader in ``reader.py``.  See npack's module docstring for the
on-disk layout and integrity/invalidation rules.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid

from nemo_tpu import obs
from nemo_tpu.obs import log as obs_log
from nemo_tpu.store.npack import (
    GROWN,
    HIT,
    NPACK_ABI_VERSION,
    NPACK_FORMAT_VERSION,
    StoreCorrupt,
    _runs_prefix_sha,
    _verify_on_load,
    classify_source,
    corpus_cache_dir,
    payload_from_molly,
    payload_from_runs,
    fingerprint_mode,
    quarantine_changed,
    quarantine_file_names,
    quarantine_files_from_snapshot,
    segment_fingerprint,
    segment_source_fp,
    segment_source_fp_positions,
    snapshot_source,
    snapshot_source_appended,
    source_from_snapshot,
    store_workers_default,
    stored_positions,
    write_segment,
    write_vocab,
)

__all__ = [
    "CorpusStore",
    "StoreCorrupt",
    "NPACK_FORMAT_VERSION",
    "NPACK_ABI_VERSION",
    "corpus_cache_dir",
    "resolve_store",
    "store_size_bytes",
    "segment_fingerprint",
    "attach_store_provenance",
]


def attach_store_provenance(obj, store_dir: str, header: dict) -> None:
    """Stamp a loaded corpus/MollyOutput with the store identity the
    analysis result cache keys on: one ``{name, n_runs, fingerprint}``
    record per segment (append order == global run order).  Set on both
    the MollyOutput and the array-only corpus objects so every consumer
    of a warm load can content-address its downstream results."""
    obj.store_dir = store_dir
    obj.store_segments = [
        {
            "name": e["name"],
            "n_runs": int(e["n_runs"]),
            "fingerprint": segment_fingerprint(e),
        }
        for e in header["segments"]
    ]

_log = obs_log.get_logger("nemo.store")


def _index_file(corpus_dir: str) -> str:
    """The layout's index file (ingest/adapters.py seam), recorded in the
    stored source so classification and the append dispatch stay
    injector-agnostic on later loads.  Unsniffable directories default to
    the Molly index — the pre-seam behavior."""
    try:
        from nemo_tpu.ingest.adapters import resolve_injector

        return resolve_injector(corpus_dir).index_file or "runs.json"
    except Exception:
        return "runs.json"


def resolve_store(arg: str | None = None) -> "CorpusStore | None":
    root = corpus_cache_dir(arg)
    return CorpusStore(root) if root else None


def store_size_bytes(store_dir: str) -> int:
    """On-disk bytes of one .npack store (every file, stray tmp included) —
    the single size measure shared by eviction and the bench's ingest tier."""
    return sum(
        os.path.getsize(os.path.join(dp, f))
        for dp, _, fs in os.walk(store_dir)
        for f in fs
    )


def _max_store_bytes() -> int:
    """Cache-root size cap (bytes): ``NEMO_STORE_MAX_GB`` (default 16; 0 /
    junk disables).  A corpus store mirrors whole corpora — arrays plus
    every serialized string — so unlike the jit/SVG caches it needs
    eviction: throwaway generated corpora would otherwise accumulate
    orphaned stores forever under the default-on ~/.cache root."""
    env = os.environ.get("NEMO_STORE_MAX_GB", "").strip()
    try:
        gb = float(env) if env else 16.0
    except ValueError:
        gb = 0.0
    return int(gb * 1e9) if gb > 0 else 0


class _Lock:
    """fcntl advisory lock serializing writers of ONE store (the lock file
    sits beside its .npack directory, so corpora never serialize each
    other); no-op where fcntl is unavailable (non-POSIX)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._fh = None

    def __enter__(self):
        try:
            import fcntl

            self._fh = open(self.path, "w")
            fcntl.flock(self._fh, fcntl.LOCK_EX)
        except (ImportError, OSError):
            self._fh = None
        return self

    def __exit__(self, *exc):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class CorpusStore:
    """One cache root holding ``.npack`` stores keyed by source realpath."""

    def __init__(self, root: str) -> None:
        self.root = root

    # ------------------------------------------------------------- plumbing

    def store_dir(self, corpus_dir: str) -> str:
        real = os.path.realpath(corpus_dir)
        key = hashlib.sha256(real.encode()).hexdigest()[:12]
        # Basename from the REALPATH, like the hash: a symlink alias must
        # map to the same store, not a second full mirror of the corpus.
        base = os.path.basename(real) or "corpus"
        safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in base)[:64]
        return os.path.join(self.root, f"{safe}-{key}.npack")

    def _lock(self, store_dir: str) -> _Lock:
        os.makedirs(self.root, exist_ok=True)
        return _Lock(f"{store_dir}.lock")

    #: _read_header sentinel: a store EXISTS but cannot be trusted —
    #: written by another format/ABI generation, or its header is
    #: unreadable/corrupt.  Stale, not miss: a fleet-wide version bump (or
    #: disk corruption) must be visible in the metrics as invalidation,
    #: not cold caches.
    _HEADER_UNTRUSTED = object()

    def _read_header(self, store_dir: str):
        """dict, None (no store at all), or _HEADER_UNTRUSTED."""
        path = os.path.join(store_dir, "header.json")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                header = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as ex:
            _log.warning(
                "store.header_unreadable",
                store=store_dir,
                error=f"{type(ex).__name__}: {ex}",
                detail="treating the store as stale; the next populate "
                "replaces it",
            )
            return self._HEADER_UNTRUSTED
        if (
            header.get("format") != NPACK_FORMAT_VERSION
            or header.get("abi") != NPACK_ABI_VERSION
        ):
            _log.warning(
                "store.version_mismatch",
                store=store_dir,
                format=header.get("format"),
                abi=header.get("abi"),
                expected_format=NPACK_FORMAT_VERSION,
                expected_abi=NPACK_ABI_VERSION,
            )
            return self._HEADER_UNTRUSTED
        return header

    # ---------------------------------------------------------------- probe

    def probe(self, corpus_dir: str) -> str:
        """'hit' / 'grown' / 'stale' / 'miss' without mapping any shard —
        the cheap check ingest-mode resolution uses on lib-less hosts."""
        header = self._read_header(self.store_dir(corpus_dir))
        if header is None:
            return "miss"
        if header is self._HEADER_UNTRUSTED:
            return "stale"
        return classify_source(header, corpus_dir)

    # ----------------------------------------------------------------- load

    def load_packed(self, corpus_dir: str):
        """Warm load: a packed MollyOutput served from the store, or None
        (miss / stale / corrupt — counted and logged, never raised: the
        caller falls back to the parse path)."""
        return self._load(corpus_dir, build_molly=True)

    def load_corpus(self, corpus_dir: str):
        """Warm load of JUST the packed corpus (a StoreCorpus / NativeCorpus
        duck), skipping the per-run MollyOutput construction — for callers
        that only dispatch arrays (pack_molly_dir_host, the analyze_dir
        producers), so a 100k-run warm pack pays zero per-run Python work.
        Same miss/stale semantics and metrics as load_packed."""
        return self._load(corpus_dir, build_molly=False)

    def _load(self, corpus_dir: str, build_molly: bool):
        from nemo_tpu.store.reader import build_corpus, molly_from_corpus, open_segments

        store_dir = self.store_dir(corpus_dir)
        t0 = time.perf_counter()
        with obs.span("ingest:store_load", dir=os.path.basename(corpus_dir)):
            header = self._read_header(store_dir)
            if header is None:
                obs.metrics.inc("store.miss")
                return None
            if header is self._HEADER_UNTRUSTED:
                obs.metrics.inc("store.stale")
                return None
            state = classify_source(header, corpus_dir)
            if state == GROWN:
                header = self._append(store_dir, header, corpus_dir)
                if header is None:
                    obs.metrics.inc("store.stale")
                    return None
                state = HIT
            if state != HIT:
                obs.metrics.inc("store.stale")
                _log.warning(
                    "store.stale",
                    store=store_dir,
                    corpus=corpus_dir,
                    detail="source fingerprint changed; falling back to the parse path",
                )
                return None
            try:
                seg_readers, vocab_rd, mapped = open_segments(
                    store_dir, header, verify=_verify_on_load()
                )
                corpus = build_corpus(store_dir, header, seg_readers, vocab_rd)
                # Row -> source-position mapping for the lazy runs.json
                # trio: quarantine/repair stores hold a row SUBSET, so the
                # identity mapping would read the wrong entries (ISSUE 9).
                pos = (
                    stored_positions(header)
                    if (header.get("quarantined") or any(
                        "positions" in s for s in header["segments"]
                    ))
                    else None
                )
                out = (
                    molly_from_corpus(corpus, corpus_dir, positions=pos)
                    if build_molly
                    else corpus
                )
                # Segment identities ride on the loaded object: the result
                # cache (store/rcache.py) keys analysis outputs on them.
                attach_store_provenance(corpus, store_dir, header)
                if out is not corpus:
                    attach_store_provenance(out, store_dir, header)
                # The quarantine set rides too (ISSUE 9): a warm load must
                # reproduce the cold parse's "Degraded runs" section
                # byte-for-byte (the per-file stat fingerprints are store
                # bookkeeping, not report content — stripped here).
                qrecs = header.get("quarantined") or ()
                if qrecs:
                    q = [
                        {k: v for k, v in rec.items() if k != "files"}
                        for rec in qrecs
                    ]
                    corpus.quarantined = q
                    if out is not corpus:
                        out.quarantined = q
                    obs.metrics.inc("ingest.quarantined", len(q))
            except (StoreCorrupt, OSError, ValueError, KeyError) as ex:
                obs.metrics.inc("store.stale")
                _log.error(
                    "store.corrupt",
                    store=store_dir,
                    corpus=corpus_dir,
                    error=f"{type(ex).__name__}: {ex}",
                    detail="falling back to the parse path; the next populate "
                    "overwrites the bad store",
                )
                return None
            obs.metrics.inc("store.hit")
            obs.metrics.inc("store.bytes_mapped", mapped)
            obs.metrics.observe("store.load_s", time.perf_counter() - t0)
            try:
                # Last-use stamp for the size-cap eviction: loads only READ,
                # so without this a hot store looks as cold as an orphan.
                os.utime(os.path.join(store_dir, "header.json"))
            except OSError:
                pass
            _log.info(
                "store.hit",
                corpus=corpus_dir,
                runs=corpus.n_runs,
                segments=len(header["segments"]),
                mapped_mb=round(mapped / 1e6, 1),
                seconds=round(time.perf_counter() - t0, 3),
            )
            return out

    # ------------------------------------------------------------- populate

    def snapshot(self, corpus_dir: str) -> dict:
        """Pre-parse source snapshot: callers that are about to PARSE the
        directory take one first and hand it to :meth:`put`, so a file
        mutated during the (minutes-long at scale) parse mismatches the
        stored fingerprint on the next load instead of being served as a
        HIT."""
        return snapshot_source(corpus_dir, index_file=_index_file(corpus_dir))

    def put(self, corpus_dir: str, molly, snapshot: dict | None = None):
        """Populate (or replace) the store for ``corpus_dir`` from a parsed
        MollyOutput — packed-first (native) or object-loader (Python), both
        producers yield bit-compatible stores.  ``snapshot`` is the
        pre-parse :meth:`snapshot` (taken now when omitted — fine when the
        directory cannot have changed since the parse).  Returns the
        written header (truthy) on success — callers that populate on the
        parse path use it to attach the segment identities the result
        cache keys on — or False (logged) on any failure: populating is
        always best-effort."""
        try:
            return self._put(corpus_dir, molly, snapshot)
        except Exception as ex:  # a cache write must never sink the pipeline
            obs.metrics.inc("store.write_failed")
            _log.warning(
                "store.write_failed",
                corpus=corpus_dir,
                error=f"{type(ex).__name__}: {ex}",
            )
            return False

    def _put(self, corpus_dir: str, molly, snapshot: dict | None = None):
        if not molly.runs:
            return False
        t0 = time.perf_counter()
        workers = store_workers_default()
        with obs.span("ingest:store_populate", dir=os.path.basename(corpus_dir)):
            payload = payload_from_molly(molly)
            snap = snapshot or snapshot_source(
                corpus_dir, index_file=_index_file(corpus_dir)
            )
            # Quarantined runs (ISSUE 9): the store persists only the
            # HEALTHY rows but records the quarantine set — each record
            # carries the stats of its run's files, so a later load serves
            # the same degraded corpus until the operator repairs a file,
            # which classifies GROWN and re-ingests exactly those
            # positions via the append path.  Their files are excluded
            # from the class fingerprints (source_from_snapshot) — a
            # repair must read as GROWN, not STALE.
            qsrc = list(getattr(molly, "quarantined", None) or [])
            qrecs = [
                dict(
                    rec,
                    files=[]
                    if rec.get("file") == "runs.json"
                    else quarantine_files_from_snapshot(snap, rec["position"]),
                )
                for rec in qsrc
            ]
            qpos = {rec["position"] for rec in qrecs}
            n_positions = payload.n_runs + len(qpos)
            source = source_from_snapshot(
                snap, n_positions, exclude=quarantine_file_names(qrecs)
            )
            source["dir"] = os.path.realpath(corpus_dir)
            final = self.store_dir(corpus_dir)
            tmp = f"{final}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
            os.makedirs(tmp, exist_ok=True)
            try:
                seg_entry = write_segment(os.path.join(tmp, "seg-000"), payload, workers)
                from nemo_tpu.utils import chaos

                chaos.on_store_publish()
                # Per-segment SOURCE fingerprint: the run files these rows
                # came from (spacetime DOTs included — content the packed
                # arrays don't mirror); part of the segment's identity for
                # the result cache.
                if qpos:
                    healthy = sorted(set(range(n_positions)) - qpos)
                    seg_entry["positions"] = healthy
                    seg_entry["source_fp"] = segment_source_fp_positions(snap, healthy)
                else:
                    seg_entry["source_fp"] = segment_source_fp(snap, 0, payload.n_runs)
                vshard = write_vocab(
                    os.path.join(tmp, "vocab-0001.bin"), _VocabView(payload.vocab)
                )
                header = {
                    "format": NPACK_FORMAT_VERSION,
                    "abi": NPACK_ABI_VERSION,
                    "source": source,
                    "pre_tid": 0,
                    "post_tid": 1,
                    "vocab_shard": vshard,
                    "segments": [seg_entry],
                }
                if qrecs:
                    header["quarantined"] = qrecs
                with open(os.path.join(tmp, "header.json"), "w", encoding="utf-8") as fh:
                    json.dump(header, fh, indent=1)
                with self._lock(final):
                    doomed = None
                    if os.path.isdir(final):
                        doomed = f"{final}.doomed-{uuid.uuid4().hex[:8]}"
                        os.rename(final, doomed)
                    os.rename(tmp, final)
                if doomed:
                    shutil.rmtree(doomed, ignore_errors=True)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            self._evict_over_cap(keep=final)
        obs.metrics.inc("store.populate")
        _log.info(
            "store.populated",
            corpus=corpus_dir,
            runs=payload.n_runs,
            store=final,
            seconds=round(time.perf_counter() - t0, 2),
        )
        return header

    # ------------------------------------------------------------- eviction

    #: Crash leftovers (`*.npack.tmp-*` populate dirs, `*.npack.doomed-*`
    #: replace victims) older than this are swept at populate time; younger
    #: ones may belong to a LIVE concurrent populate and are left alone.
    _WRECKAGE_MAX_AGE_S = 3600.0

    def _evict_over_cap(self, keep: str) -> None:
        """Bound the cache root at NEMO_STORE_MAX_GB: when the .npack
        directories exceed the cap, evict least-recently-USED stores
        (header.json mtime — stamped on every hit) until under, never the
        one just written.  Aged crash leftovers (interrupted populates /
        replaces, which the '.npack' filter below would never see) are
        swept first regardless of the cap.  Best effort; called at
        populate time, the only moment the root grows.  Lock FILES are
        never swept: deleting one a live writer holds open would hand the
        next opener a fresh inode and break the mutual exclusion."""
        try:
            now = time.time()

            def sweep(path: str) -> None:
                try:
                    if now - os.path.getmtime(path) < self._WRECKAGE_MAX_AGE_S:
                        return
                except OSError:
                    return
                try:
                    (shutil.rmtree if os.path.isdir(path) else os.remove)(path)
                except OSError:
                    return
                obs.metrics.inc("store.gc_wreckage")
                _log.info("store.gc_wreckage", path=path)

            for name in os.listdir(self.root):
                path = os.path.join(self.root, name)
                if ".npack.tmp-" in name or ".npack.doomed-" in name:
                    sweep(path)
                elif name.endswith(".npack"):
                    # Interrupted APPENDS leave leftovers INSIDE a store:
                    # seg-NNN.tmp-* segment dirs and header.json.tmp-*.
                    try:
                        inner = os.listdir(path)
                    except OSError:
                        continue
                    for child in inner:
                        if ".tmp-" in child:
                            sweep(os.path.join(path, child))
        except OSError:
            pass
        cap = _max_store_bytes()
        if not cap:
            return
        try:
            stores = []
            for name in os.listdir(self.root):
                if not name.endswith(".npack"):
                    continue
                path = os.path.join(self.root, name)
                size = store_size_bytes(path)
                try:
                    used = os.path.getmtime(os.path.join(path, "header.json"))
                except OSError:
                    used = 0.0  # headerless wreckage evicts first
                stores.append((used, size, path))
            total = sum(s for _, s, _ in stores)
            if total <= cap:
                return
            for used, size, path in sorted(stores):
                if total <= cap:
                    break
                if os.path.abspath(path) == os.path.abspath(keep):
                    continue
                shutil.rmtree(path, ignore_errors=True)
                total -= size
                obs.metrics.inc("store.evicted")
                _log.info(
                    "store.evicted", store=path, freed_mb=round(size / 1e6, 1),
                    cap_gb=round(cap / 1e9, 1),
                )
        except OSError as ex:
            _log.warning("store.evict_failed", root=self.root, error=str(ex))

    # --------------------------------------------------------------- append

    def _append(self, store_dir: str, header: dict, corpus_dir: str) -> dict | None:
        """The corpus directory GREW (incremental sweep): pack only the new
        runs (pure-Python loader, positions >= n_stored) against the stored
        vocabulary and publish them as a fresh segment.  Returns the new
        header, or None when the old entries cannot be confirmed unchanged
        (the caller then treats the store as stale).  Dispatches on the
        stored index file: Molly's runs.json rides the per-run-file path,
        single-document layouts (trace.json) the index-delta path."""
        try:
            src = header.get("source") or {}
            index_file = src.get("index_file") or "runs.json"
            if index_file != "runs.json":
                return self._append_index_locked(
                    store_dir, header, corpus_dir, index_file
                )
            return self._append_locked(store_dir, header, corpus_dir)
        except Exception as ex:
            obs.metrics.inc("store.append_failed")
            _log.warning(
                "store.append_failed",
                corpus=corpus_dir,
                error=f"{type(ex).__name__}: {ex}",
            )
            return None

    def _append_locked(self, store_dir: str, header, corpus_dir: str) -> dict | None:
        from nemo_tpu.graphs.packed import CorpusVocab
        from nemo_tpu.ingest.datatypes import RunData
        from nemo_tpu.ingest.molly import load_run_prov
        from nemo_tpu.store.reader import open_segments

        with self._lock(store_dir), obs.span(
            "ingest:store_append", dir=os.path.basename(corpus_dir)
        ):
            # Re-read under the lock: a concurrent appender may have won.
            header = self._read_header(store_dir)
            if not isinstance(header, dict):
                return None
            state = classify_source(header, corpus_dir)
            if state == HIT:
                return header
            if state != GROWN:
                return None
            src = header["source"]
            n_old = int(src["n_runs"])
            # Repair candidates (ISSUE 9): quarantined positions whose
            # watched files' stats moved — the operator repaired them, and
            # this append re-ingests exactly those positions alongside any
            # appended tail.
            qrecs_old = list(header.get("quarantined") or ())
            repair_pos = {
                int(r["position"]) for r in quarantine_changed(corpus_dir, qrecs_old)
            }
            # Snapshot BEFORE parsing anything: a file mutated while the
            # tail parse below runs then mismatches the fingerprint this
            # append publishes, so the NEXT load re-parses (fail-safe).
            # In fast fingerprint mode the snapshot is PARTIAL — names
            # enumeration + stats for only runs.json, the new run files,
            # the repair candidates, and the load-check sample — so the
            # append wall scales with the growth, not the corpus (a full
            # per-file stat pass is ~40 s on a 9p-mounted 10x corpus).
            snap = (
                snapshot_source(corpus_dir)
                if fingerprint_mode() == "full"
                else snapshot_source_appended(
                    corpus_dir, n_old, extra_positions=repair_pos
                )
            )
            with open(os.path.join(corpus_dir, "runs.json"), "r", encoding="utf-8") as fh:
                raw_runs = json.load(fh)
            if len(raw_runs) < n_old or (len(raw_runs) == n_old and not repair_pos):
                return None
            # Old-entry confirmation: prefer the strong byte-prefix check (a
            # stable serializer keeps the first n entries' bytes identical).
            # Otherwise compare the baked-in iteration/status of EVERY old
            # entry against the stored arrays, plus the full serialized head
            # fragment (failureSpec/model/messages included) of a bounded
            # <=64-entry spread — so a bulk rewrite of old entries cannot
            # splice stale heads; a single mutated unsampled entry with
            # stable iteration/status is outside the bounded budget, like
            # the fingerprint sample (npack.py docstring).  The per-run
            # provenance FILES are fingerprinted individually either way.
            strong = src.get("runs_prefix_sha") and _runs_prefix_sha(
                corpus_dir, (src.get("runs_json") or [0])[0]
            ) == src.get("runs_prefix_sha")
            seg_readers, vocab_rd, _ = open_segments(store_dir, header, verify=False)
            if not strong:
                from nemo_tpu.ingest.datatypes import RunData as _RunData
                from nemo_tpu.store.npack import _head_bytes
                from nemo_tpu.store.reader import build_corpus

                old = build_corpus(store_dir, header, seg_readers, vocab_rd)

                def refused(row: int, why: str) -> None:
                    _log.warning(
                        "store.append_refused", corpus=corpus_dir, row=row,
                        detail=why,
                    )

                # Stored row -> source position: identity for legacy
                # stores, explicit per-segment position lists once
                # quarantine/repair segments exist (ISSUE 9).
                rows_pos = stored_positions(header)
                n_stored = len(rows_pos)
                for row, pos in enumerate(rows_pos):
                    r = raw_runs[pos]
                    if int(r.get("iteration", 0)) != int(old.iteration[row]) or (
                        (r.get("status", "") == "success") != bool(old.success[row])
                    ):
                        refused(pos, "old runs.json entries changed; store is stale")
                        return None
                stride = max(1, n_stored // 64)
                check = sorted(set(range(0, n_stored, stride)) | {0, n_stored - 1})
                for row in check:
                    pos = rows_pos[row]
                    if _head_bytes(_RunData.from_json(raw_runs[pos])) != old.run_head_json(row):
                        refused(pos, "old run head fragment changed; store is stale")
                        return None
            # Stored vocabulary, extended in place by the new graphs ("pre"/
            # "post" re-intern to their pinned 0/1).
            from nemo_tpu.store.reader import _decode_vocab

            vocab = CorpusVocab()
            for part in ("tables", "labels", "times"):
                v = getattr(vocab, part)
                for s in _decode_vocab(vocab_rd, part):
                    v.intern(s)
            # Candidate positions: the appended tail plus any repaired
            # quarantined positions; each parses under the same per-run
            # isolation as the loader (ISSUE 9) — a malformed candidate
            # joins/stays in the quarantine instead of failing the append.
            from nemo_tpu.ingest.molly import quarantine_record
            from nemo_tpu.utils.env import quarantine_enabled

            quarantine = quarantine_enabled()
            candidates = sorted(repair_pos | set(range(n_old, len(raw_runs))))
            new_runs, new_positions, new_q = [], [], []
            for pos in candidates:
                try:
                    run = RunData.from_json(raw_runs[pos])
                except Exception as ex:
                    if not quarantine:
                        return None  # stale -> the caller reparses, loudly
                    new_q.append(quarantine_record(pos, None, "runs.json", ex))
                    continue
                try:
                    load_run_prov(corpus_dir, pos, run)
                except Exception as ex:
                    if not quarantine:
                        return None
                    cond = "post" if run.pre_prov is not None else "pre"
                    new_q.append(
                        quarantine_record(
                            pos, run.iteration, f"run_{pos}_{cond}_provenance.json", ex
                        )
                    )
                    continue
                new_runs.append(run)
                new_positions.append(pos)
            for rec in new_q:
                rec["files"] = (
                    []
                    if rec["file"] == "runs.json"
                    else quarantine_files_from_snapshot(snap, rec["position"])
                )
                obs.metrics.inc("ingest.quarantined")
            kept_q = [r for r in qrecs_old if int(r["position"]) not in repair_pos]
            final_q = sorted(kept_q + new_q, key=lambda r: int(r["position"]))

            seg_name = f"seg-{len(header['segments']):03d}"
            segments = header["segments"]
            if new_runs:
                payload = payload_from_runs(new_runs, vocab)
                workers = store_workers_default()
                tmp_seg = os.path.join(
                    store_dir, f"{seg_name}.tmp-{uuid.uuid4().hex[:8]}"
                )
                try:
                    seg_entry = write_segment(tmp_seg, payload, workers)
                    seg_entry["name"] = seg_name
                    # Position-set fingerprint: equals the old contiguous
                    # range fp when the segment IS the contiguous tail.
                    seg_entry["source_fp"] = segment_source_fp_positions(
                        snap, new_positions
                    )
                    if final_q or qrecs_old or new_positions != list(
                        range(n_old, n_old + len(new_positions))
                    ):
                        seg_entry["positions"] = list(new_positions)
                    os.rename(tmp_seg, os.path.join(store_dir, seg_name))
                except BaseException:
                    shutil.rmtree(tmp_seg, ignore_errors=True)
                    raise
                segments = segments + [seg_entry]
            elif not new_q and not repair_pos:
                return None
            # New vocab generation (old file kept: an in-flight reader of the
            # old header still resolves), then the atomic commit point: the
            # header swap.  A no-new-runs publish (every candidate still
            # quarantined) interned nothing — keep the current vocab shard
            # untouched (rewriting it in place would race live readers) and
            # update only source + quarantine bookkeeping so the next load
            # doesn't re-attempt the same repairs.
            if new_runs:
                vshard = write_vocab(
                    os.path.join(store_dir, f"vocab-{len(segments):04d}.bin"),
                    _VocabView(vocab),
                )
            else:
                vshard = header["vocab_shard"]
            source = source_from_snapshot(
                snap, len(raw_runs), exclude=quarantine_file_names(final_q)
            )
            source["dir"] = os.path.realpath(corpus_dir)
            header = dict(
                header,
                source=source,
                vocab_shard=vshard,
                segments=segments,
            )
            header["quarantined"] = final_q
            if not final_q:
                header.pop("quarantined", None)
            tmp_header = os.path.join(store_dir, f"header.json.tmp-{uuid.uuid4().hex[:8]}")
            with open(tmp_header, "w", encoding="utf-8") as fh:
                json.dump(header, fh, indent=1)
            os.replace(tmp_header, os.path.join(store_dir, "header.json"))
        obs.metrics.inc("store.append")
        _log.info(
            "store.appended",
            corpus=corpus_dir,
            new_runs=len(new_runs),
            repaired=len([p for p in new_positions if p < n_old]),
            quarantined=len(final_q),
            total_runs=len(raw_runs),
            segment=seg_name if new_runs else None,
        )
        return header

    def _append_index_locked(
        self, store_dir: str, header, corpus_dir: str, index_file: str
    ) -> dict | None:
        """Index-delta append for single-document layouts (ingest/adapters
        injectors whose whole sweep lives INSIDE the index file, trace.json
        first): growth rewrites the one document, so there are no new
        per-run files to fingerprint — instead the injector's
        ``index_runs`` seam re-opens the document, the stored entries are
        confirmed unchanged (baked-in id/status of EVERY row plus the full
        canonical head fragment of a bounded <=64-row spread, the same
        budget as the runs.json weak check), and only entries past the
        stored count pack into a fresh segment.  This is what keeps the
        live watch loop O(new runs) for non-Molly injectors."""
        from nemo_tpu.graphs.packed import CorpusVocab
        from nemo_tpu.ingest.adapters import INJECTORS
        from nemo_tpu.ingest.molly import quarantine_record
        from nemo_tpu.store.npack import _head_bytes
        from nemo_tpu.store.reader import _decode_vocab, build_corpus, open_segments
        from nemo_tpu.utils.env import quarantine_enabled

        inj = next(
            (c for c in INJECTORS.values() if c.index_file == index_file), None
        )
        if inj is None:
            return None  # no registered injector owns this layout any more
        with self._lock(store_dir), obs.span(
            "ingest:store_append", dir=os.path.basename(corpus_dir)
        ):
            # Re-read under the lock: a concurrent appender may have won.
            header = self._read_header(store_dir)
            if not isinstance(header, dict):
                return None
            state = classify_source(header, corpus_dir)
            if state == HIT:
                return header
            if state != GROWN:
                return None
            src = header["source"]
            n_old = int(src["n_runs"])
            # Snapshot BEFORE parsing (same fail-safe direction as the
            # runs.json append); the fast-mode partial snapshot stats
            # nothing beyond the index + sample here — this layout has no
            # per-run files.
            snap = (
                snapshot_source(corpus_dir, index_file=index_file)
                if fingerprint_mode() == "full"
                else snapshot_source_appended(
                    corpus_dir, n_old, index_file=index_file
                )
            )
            idx = inj.index_runs(corpus_dir)
            if idx is None:
                return None
            n_total, parse_entry, entry_head = idx
            if n_total < n_old:
                return None
            qrecs_old = list(header.get("quarantined") or ())
            q_old_pos = {int(r["position"]) for r in qrecs_old}
            if n_total == n_old and not q_old_pos:
                return None

            def refused(pos: int, why: str) -> None:
                _log.warning(
                    "store.append_refused", corpus=corpus_dir, row=pos, detail=why
                )

            # Old-entry confirmation.  The document was REWRITTEN (that is
            # what growth looks like here) and its object wrapper's tail
            # moves on every append, so there is no byte-prefix shortcut:
            # verify every stored row's identity pair, then re-parse a
            # bounded spread through the injector's own converter and
            # compare the canonical head fragments — which also catches a
            # changed sweep-level spec, since it bakes into every head.
            seg_readers, vocab_rd, _ = open_segments(store_dir, header, verify=False)
            old = build_corpus(store_dir, header, seg_readers, vocab_rd)
            rows_pos = stored_positions(header)
            n_stored = len(rows_pos)
            try:
                for row, pos in enumerate(rows_pos):
                    it, success = entry_head(pos)
                    if it != int(old.iteration[row]) or success != bool(
                        old.success[row]
                    ):
                        refused(
                            pos, f"old {index_file} entries changed; store is stale"
                        )
                        return None
                stride = max(1, n_stored // 64)
                check = sorted(set(range(0, n_stored, stride)) | {0, n_stored - 1})
                for row in check:
                    pos = rows_pos[row]
                    if _head_bytes(parse_entry(pos)) != old.run_head_json(row):
                        refused(
                            pos, "old run head fragment changed; store is stale"
                        )
                        return None
            except Exception as ex:
                refused(
                    -1,
                    f"old {index_file} entry no longer parses "
                    f"({type(ex).__name__}: {ex}); store is stale",
                )
                return None
            # Stored vocabulary, extended in place by the new graphs.
            vocab = CorpusVocab()
            for part in ("tables", "labels", "times"):
                v = getattr(vocab, part)
                for s in _decode_vocab(vocab_rd, part):
                    v.intern(s)
            # Candidates: the appended tail plus EVERY previously
            # quarantined position — a single document has no per-file
            # repair tripwire, so each index rewrite re-attempts the
            # quarantined entries (free: the document is already in hand).
            quarantine = quarantine_enabled()
            candidates = sorted(q_old_pos | set(range(n_old, n_total)))
            new_runs, new_positions, new_q = [], [], []
            for pos in candidates:
                try:
                    run = parse_entry(pos)
                except Exception as ex:
                    if not quarantine:
                        return None  # stale -> the caller reparses, loudly
                    rid = None
                    try:
                        rid = entry_head(pos)[0]
                    except Exception:  # lint: allow-silent-except — the entry already failed to parse (quarantined just below); the head probe only enriches the record with an iteration id
                        pass
                    new_q.append(quarantine_record(pos, rid, index_file, ex))
                    continue
                new_runs.append(run)
                new_positions.append(pos)
            for rec in new_q:
                rec["files"] = []  # no watched files: repairs ride the index stat
                obs.metrics.inc("ingest.quarantined")
            final_q = sorted(new_q, key=lambda r: int(r["position"]))

            seg_name = f"seg-{len(header['segments']):03d}"
            segments = header["segments"]
            if new_runs:
                payload = payload_from_runs(new_runs, vocab)
                tmp_seg = os.path.join(
                    store_dir, f"{seg_name}.tmp-{uuid.uuid4().hex[:8]}"
                )
                try:
                    seg_entry = write_segment(
                        tmp_seg, payload, store_workers_default()
                    )
                    seg_entry["name"] = seg_name
                    # No per-run source files on this layout: the position
                    # fingerprint is empty and content identity rides the
                    # packed-shard checksums + the index stat instead.
                    seg_entry["source_fp"] = segment_source_fp_positions(
                        snap, new_positions
                    )
                    if final_q or qrecs_old or new_positions != list(
                        range(n_old, n_old + len(new_positions))
                    ):
                        seg_entry["positions"] = list(new_positions)
                    os.rename(tmp_seg, os.path.join(store_dir, seg_name))
                except BaseException:
                    shutil.rmtree(tmp_seg, ignore_errors=True)
                    raise
                segments = segments + [seg_entry]
            elif not new_q:
                return None
            if new_runs:
                vshard = write_vocab(
                    os.path.join(store_dir, f"vocab-{len(segments):04d}.bin"),
                    _VocabView(vocab),
                )
            else:
                vshard = header["vocab_shard"]
            source = source_from_snapshot(snap, n_total)
            source["dir"] = os.path.realpath(corpus_dir)
            header = dict(
                header, source=source, vocab_shard=vshard, segments=segments
            )
            header["quarantined"] = final_q
            if not final_q:
                header.pop("quarantined", None)
            tmp_header = os.path.join(
                store_dir, f"header.json.tmp-{uuid.uuid4().hex[:8]}"
            )
            with open(tmp_header, "w", encoding="utf-8") as fh:
                json.dump(header, fh, indent=1)
            os.replace(tmp_header, os.path.join(store_dir, "header.json"))
        obs.metrics.inc("store.append")
        _log.info(
            "store.appended",
            corpus=corpus_dir,
            new_runs=len(new_runs),
            repaired=len([p for p in new_positions if p < n_old]),
            quarantined=len(final_q),
            total_runs=n_total,
            segment=seg_name if new_runs else None,
        )
        return header


class _VocabView:
    """Adapter: write_vocab consumes either a CorpusVocab (``.strings``) or
    a plain {part: list[str]} dict."""

    def __init__(self, vocab) -> None:
        if isinstance(vocab, dict):
            self.tables = vocab["tables"]
            self.labels = vocab["labels"]
            self.times = vocab["times"]
        else:
            self.tables = vocab.tables
            self.labels = vocab.labels
            self.times = vocab.times
