"""The ``.npack`` persistent corpus store: parse once, mmap forever.

PR 3 made analysis so fast that the 10x-scale wall is ingest-bound — the
Molly JSON parse alone was ~78 s of a ~133 s run — yet every invocation
re-parsed the same immutable fault-injection corpora.  This module is the
training-stack-style data layer: a versioned, checksummed, memory-mapped
binary corpus format that persists EXACTLY what the ETL produces —

  * the packed ``[B,V]``/``[B,E]`` cond batch arrays (graphs/packed.py
    layout, the native engine's padding values: -1 table/label/time ids,
    0 type/edge ids, False masks),
  * the corpus vocabularies (tables/labels/times, "pre"/"post" pinned 0/1),
  * every per-run serialized string the report path splices verbatim:
    namespaced provenance JSON, the canonical debugging.json head fragment,
    the joined node-id list, plus status and holds-map keys —

so a warm load is ``np.memmap`` of each shard plus a small JSON header, and
the resulting MollyOutput is bit-interchangeable with the packed-first
loader's (ingest/native.py:load_molly_output_packed): same RawProv splices,
same LazyRunData head fragments, same arrays.  No C++ toolchain is needed to
LOAD a store, so lib-less deployments get packed-path speed too.

Layout (one directory per source corpus, keyed by realpath hash)::

    <root>/<basename>-<hash12>.npack/
      header.json            format/ABI versions, source fingerprint,
                             segment + shard manifests (offsets, checksums)
      vocab-<n>.bin          tables/labels/times blobs (rewritten-by-
                             generation on append; old generations kept so
                             in-flight readers of the old header survive)
      seg-000/
        arrays_pre.bin       the 12 packed arrays of the pre condition
        arrays_post.bin      ... and of the post condition
        runs.bin             iteration / success
        meta.bin             status + holds-key + head-fragment blobs
        strings_pre_000.bin  prov JSON + node-id blobs, chunked by row
        strings_post_000.bin   range so ingest writes shards in parallel
      seg-001/ ...           appended segments (incremental sweeps)

Integrity & invalidation:

  * every shard carries a CRC32 (verified on load unless
    ``NEMO_STORE_VERIFY=off``) and a SHA-256 (audited by
    tools/store_inspect.py);
  * the header records a fingerprint over the Molly directory's file
    names+sizes+mtimes, split into old-run / other / new-run classes so a
    GROWN directory (an incremental sweep appended runs) is distinguished
    from a STALE one (anything else changed);
  * format/ABI mismatches, fingerprint mismatches, and checksum failures
    all fall back LOUDLY to the parse path (``store.stale`` metric +
    warning log).  Detection bounds: the default ``fast`` fingerprint
    catches every entry add/remove/rename and any mutation touching
    runs.json, the dir mtime, or the stat sample — an IN-PLACE rewrite of
    a single unsampled provenance file in a huge corpus is outside its
    budget (Molly corpora are write-once per run); set
    ``NEMO_STORE_FINGERPRINT=full`` where that assumption does not hold.

Appending packs only the NEW runs (pure-Python loader, positions >=
n_stored) against the stored vocabulary, writes them as a fresh segment,
and atomically swaps the header.  Append-then-load is decoded-equal to a
repack-from-scratch (same vocabulary SET, same report bytes); raw integer
ids may differ because interning order differs, which nothing downstream
observes (everything resolves through the vocab).

Quarantine (ISSUE 9): a store populated from a quarantining ingest
persists only the HEALTHY rows, plus a ``quarantined`` header list whose
records carry per-file stat fingerprints.  Those files are EXCLUDED from
the class fingerprints, watched individually instead: unchanged -> the
same degraded corpus serves as a HIT; changed (repaired) -> GROWN, and
the append path re-ingests exactly the repaired positions as a new
segment (segment entries gain an explicit ``positions`` list once rows
are non-contiguous).  Caveat: repaired runs land in APPEND order, so a
post-repair load equals a from-scratch reparse up to run ordering — the
next full repopulate restores source order.  A quarantined runs.json
ENTRY (as opposed to a provenance file) is repaired by editing runs.json
itself, which the prefix-sha/stat checks classify STALE -> loud full
repopulate, the always-correct path.

Concurrency: writers serialize on an ``fcntl`` lock file and publish via
atomic rename, so concurrent populates of one corpus cannot tear a store;
readers never lock (POSIX keeps their mmaps alive across a concurrent
swap, and a reader that loses the race falls back to the parse path).
"""

from __future__ import annotations

import hashlib
import json
import os
import zlib
from dataclasses import dataclass

import numpy as np

#: On-disk layout version: bump when the shard/region/blob encoding changes.
NPACK_FORMAT_VERSION = 1
#: Content ABI: the contract for WHAT is persisted (array set, string blobs,
#: padding values).  Mirrors the ingest engines' ABI — bump in lockstep with
#: native nemo_abi_version when the packed layout changes.
NPACK_ABI_VERSION = 5

_ALIGN = 64

#: Region set of one condition's shard, in NativeCondBatch field order.
_COND_ARRAYS = (
    ("table_id", "bv"),
    ("label_id", "bv"),
    ("time_id", "bv"),
    ("type_id", "bv"),
    ("is_goal", "bv"),
    ("node_mask", "bv"),
    ("edge_src", "be"),
    ("edge_dst", "be"),
    ("edge_mask", "be"),
    ("n_nodes", "b"),
    ("n_goals", "b"),
    ("chain_linear", "b"),
)


# ---------------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------------


def corpus_cache_dir(arg: str | None = None) -> str | None:
    """Resolve the corpus store root: an explicit argument wins (``off`` /
    ``0`` / ``none`` / ``false`` disables -> None), else ``NEMO_CORPUS_CACHE``,
    else ``~/.cache/nemo_tpu/corpus`` beside the SVG and jit-artifact caches
    (report/render.py:svg_cache_dir — same default-on policy)."""
    env = arg if arg is not None else os.environ.get("NEMO_CORPUS_CACHE")
    if env is not None:
        env = env.strip()
        if env.lower() in ("", "0", "off", "none", "false"):
            return None
        # expanduser like the default below: NEMO_CORPUS_CACHE=~/x set in a
        # non-shell context (systemd/.env/Docker ENV) must not create a
        # literal './~' directory per cwd.
        return os.path.expanduser(env)
    return os.path.join(os.path.expanduser("~"), ".cache", "nemo_tpu", "corpus")


def store_workers_default() -> int:
    """Parallel shard-writer width: NEMO_STORE_WORKERS when set (>=1; junk
    warns and falls through — the NEMO_RENDER_WORKERS policy), else
    min(8, effective cores).  Threads, not processes: the shard payloads are
    big shared numpy arrays, file writes and hashing release the GIL, and a
    spawn pool would pickle every array across."""
    import warnings

    env = os.environ.get("NEMO_STORE_WORKERS", "").strip()
    if env:
        try:
            n = int(env)
        except ValueError:
            n = 0
        if n >= 1:
            return n
        warnings.warn(
            f"NEMO_STORE_WORKERS={env!r} is not a positive integer; "
            "using min(8, cpu count)",
            stacklevel=2,
        )
    from nemo_tpu.utils import effective_cpu_count

    return max(1, min(8, effective_cpu_count()))


def _verify_on_load() -> bool:
    return os.environ.get("NEMO_STORE_VERIFY", "").strip().lower() not in (
        "0",
        "off",
        "none",
        "false",
    )


# ---------------------------------------------------------------------------
# shard files: aligned regions + checksums
# ---------------------------------------------------------------------------


def _blob_regions(name: str, rows: list[bytes]) -> list[tuple[str, np.ndarray]]:
    """A variable-length string column as two fixed regions: int64 row
    offsets [n+1] and the concatenated bytes."""
    offs = np.zeros(len(rows) + 1, dtype=np.int64)
    if rows:
        np.cumsum([len(r) for r in rows], out=offs[1:])
    data = np.frombuffer(b"".join(rows), dtype=np.uint8)
    return [(f"{name}.offsets", offs), (f"{name}.bytes", data)]


def write_shard(path: str, regions: list[tuple[str, np.ndarray]]) -> dict:
    """Write one shard file (aligned raw regions) and return its manifest:
    ``{file, nbytes, crc32, sha256, regions: [{name, dtype, shape, offset}]}``.
    Checksums cover the whole file including alignment padding."""
    crc = 0
    sha = hashlib.sha256()
    manifest: list[dict] = []
    pos = 0
    with open(path, "wb") as fh:

        def emit(buf) -> None:
            nonlocal crc, pos
            fh.write(buf)
            crc = zlib.crc32(buf, crc)
            sha.update(buf)
            pos += len(buf)

        for name, arr in regions:
            arr = np.ascontiguousarray(arr)
            pad = -pos % _ALIGN
            if pad:
                emit(b"\0" * pad)
            manifest.append(
                {
                    "name": name,
                    "dtype": arr.dtype.name,
                    "shape": list(arr.shape),
                    "offset": pos,
                }
            )
            emit(memoryview(arr).cast("B"))
    return {
        "file": os.path.basename(path),
        "nbytes": pos,
        "crc32": crc & 0xFFFFFFFF,
        "sha256": sha.hexdigest(),
        "regions": manifest,
    }


class ShardReader:
    """One mmapped shard: zero-copy region views over the raw file."""

    def __init__(self, path: str, manifest: dict) -> None:
        self.path = path
        self.manifest = manifest
        self.nbytes = int(manifest["nbytes"])
        if self.nbytes:
            self._mm = np.memmap(path, dtype=np.uint8, mode="r")
        else:  # np.memmap refuses zero-length files
            self._mm = np.zeros(0, dtype=np.uint8)
        if self._mm.size != self.nbytes:
            raise StoreCorrupt(
                f"{path}: size {self._mm.size} != manifest nbytes {self.nbytes}"
            )
        self._by_name = {r["name"]: r for r in manifest["regions"]}
        self._blobs: dict[str, BlobView] = {}

    def verify(self) -> None:
        """CRC32 over the whole file (reads every page once — still orders
        of magnitude cheaper than the JSON parse this store replaces)."""
        crc = zlib.crc32(memoryview(self._mm)) & 0xFFFFFFFF
        if crc != int(self.manifest["crc32"]):
            raise StoreCorrupt(
                f"{self.path}: crc32 {crc:#010x} != manifest "
                f"{int(self.manifest['crc32']):#010x}"
            )

    def region(self, name: str) -> np.ndarray:
        r = self._by_name[name]
        dtype = np.dtype(r["dtype"])
        shape = tuple(r["shape"])
        n = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        off = int(r["offset"])
        if off + n > self.nbytes:
            raise StoreCorrupt(f"{self.path}: region {name} overruns the file")
        return self._mm[off : off + n].view(dtype).reshape(shape)

    def blob(self, name: str) -> "BlobView":
        # Memoized per column (ISSUE 12 satellite): the report phase reads
        # one provenance blob PER RUN, and rebuilding the view — two region
        # lookups, dtype/shape decode, bounds check — per row was ~45 µs of
        # pure dispatch against a ~1 µs slice, the dominant per-run cost of
        # a warm report splice at stress scale.
        view = self._blobs.get(name)
        if view is None:
            view = self._blobs[name] = BlobView(
                self.region(f"{name}.offsets"), self.region(f"{name}.bytes")
            )
        return view


class BlobView:
    """Row accessor over an (offsets, bytes) blob pair."""

    __slots__ = ("offsets", "data", "_offs")

    def __init__(self, offsets: np.ndarray, data: np.ndarray) -> None:
        self.offsets = offsets
        self.data = data
        self._offs = None  # offsets materialized off the mmap on first row

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def row(self, i: int) -> bytes:
        # The offsets column is tiny (8 bytes/row) but per-row memmap scalar
        # indexing costs ~9 µs in numpy dispatch; one in-memory copy on the
        # first access makes every later row a plain array index.  The
        # payload bytes stay mmapped — only touched rows fault in.
        if self._offs is None:
            self._offs = np.array(self.offsets)
        o0, o1 = int(self._offs[i]), int(self._offs[i + 1])
        return self.data[o0:o1].tobytes()

    def rows(self) -> list[bytes]:
        """Every row, decoded from ONE bulk read: per-row memmap indexing
        costs ~9 µs in numpy dispatch alone, which dominates a 100k-row
        eager column (statuses) — plain bytes slicing is ~1 µs."""
        buf = self.data.tobytes()
        offs = self.offsets.tolist()
        return [buf[offs[i] : offs[i + 1]] for i in range(len(offs) - 1)]


class StoreCorrupt(RuntimeError):
    """A store exists but cannot be trusted (checksum/size/structure)."""


# ---------------------------------------------------------------------------
# source fingerprinting
# ---------------------------------------------------------------------------


#: Bounded per-file stat budget of the fast fingerprint check: enough to
#: catch a bulk regeneration (every file's mtime moves) on the first load,
#: cheap even on network filesystems where one stat costs ~100 µs.
_SAMPLE_FILES = 64


def _select_sample(entries: list) -> list:
    """The deterministic <=:data:`_SAMPLE_FILES` spread used by every
    fingerprint sample: an even stride over ``sorted(entries)`` plus the
    last element.  Membership is a pure function of the sorted entry list
    — single definition so the populate, append, and load-side selections
    can never drift."""
    base = sorted(entries)
    stride = max(1, len(base) // _SAMPLE_FILES)
    sample = base[::stride][:_SAMPLE_FILES]
    if base and base[-1] not in sample:
        sample.append(base[-1])
    return sample


def fingerprint_mode() -> str:
    """``fast`` (default): warm loads compare file NAMES (one scandir, no
    per-file stat) plus runs.json's stat plus a stored <=64-file stat
    sample — on the 9p/network filesystems this repo benches on, a full
    per-file stat scan costs more than the entire mmap load (~136 µs/stat
    observed; a 10x corpus has 300k+ files).  ``NEMO_STORE_FINGERPRINT=full``
    restores the exhaustive per-file size+mtime comparison.  POPULATE-time
    fingerprints are always full (the stat pass amortizes into the
    minutes-long parse); APPEND-time snapshots follow this mode
    (:func:`snapshot_source_appended` — stats proportional to the growth,
    not the corpus, so fast-mode appends publish no ``old_fp``/``other_fp``
    and a later ``full``-mode load repopulates)."""
    env = os.environ.get("NEMO_STORE_FINGERPRINT", "").strip().lower()
    return "full" if env == "full" else "fast"


def _fp(lines: list[str]) -> str:
    return hashlib.sha256("\n".join(sorted(lines)).encode()).hexdigest()


def snapshot_source(
    corpus_dir: str, with_stats: bool = True, index_file: str = "runs.json"
) -> dict:
    """Raw (name, size, mtime_ns) snapshot of the sweep directory, taken
    BEFORE a writer parses it: a file mutated DURING the (minutes-long at
    scale) parse then mismatches the stored pre-parse fingerprint on the
    next load — the fail-safe direction.  ``runs_prefix_sha`` is captured
    here too (the bytes could likewise change under the parse).

    ``index_file`` is the layout's index (ingest/adapters.py:
    FaultInjector.index_file — runs.json for Molly, trace.json for the
    trace layout): it legitimately changes on append, so it is pulled out
    of the class fingerprints into the separately-compared ``runs_json``
    stat slot.  The name is recorded so classification and the append
    path stay injector-agnostic on load."""
    # Dir mtime BEFORE the enumeration: entry creates/deletes/renames bump
    # it, so a load whose dir mtime still matches can skip the enumeration
    # entirely (classify_source tier 0).  Files added between this stat and
    # the scan below are included in the scan but leave the stored mtime
    # older — the next load then re-scans, which is the safe direction.
    dir_mtime_ns = os.stat(corpus_dir).st_mtime_ns
    entries: list[tuple[str, int, int]] = []
    runs_json: list[int] | None = None
    with os.scandir(corpus_dir) as it:
        for entry in it:
            name = entry.name
            if name == index_file:
                st = entry.stat()
                runs_json = [st.st_size, st.st_mtime_ns]
                continue
            if not entry.is_file(follow_symlinks=True):
                continue
            if with_stats:
                st = entry.stat()
                entries.append((name, st.st_size, st.st_mtime_ns))
            else:
                entries.append((name, 0, 0))
    return {
        "dir_mtime_ns": dir_mtime_ns,
        "runs_json": runs_json,
        "index_file": index_file,
        "entries": entries,
        "with_stats": with_stats,
        "runs_prefix_sha": _runs_prefix_sha(
            corpus_dir, (runs_json or [0])[0], index_file
        )
        if with_stats
        else None,
    }


def snapshot_source_appended(
    corpus_dir: str,
    n_old: int,
    extra_positions: set | None = None,
    index_file: str = "runs.json",
) -> dict:
    """Partial pre-parse snapshot for the APPEND path in ``fast``
    fingerprint mode: one names-only enumeration plus stats for exactly
    the files the published fingerprint will read — runs.json, the NEW
    run files (positions >= ``n_old``; their stats become the appended
    segment's ``source_fp``), and the deterministic <=
    :data:`_SAMPLE_FILES` spread the fast load check verifies.  A full
    :func:`snapshot_source` stats EVERY file, which is O(corpus) syscalls
    per append (~136 µs each on the 9p/network filesystems this repo
    benches on: a 10x corpus holds 300k+ files = ~40 s of stats to append
    a 5% sweep increment); this keeps the append wall proportional to the
    GROWTH, which is the whole point of the append path.

    The exhaustive per-class stat fingerprints (``old_fp``/``other_fp``)
    are consequently absent from the published source: a later
    ``NEMO_STORE_FINGERPRINT=full`` load finds no stored ``old_fp`` to
    compare against and classifies STALE — a loud repopulate, the
    conservative direction (switching to the stricter mode re-verifies
    from scratch; it can never serve stale bytes).  Old-file stats are
    untouched here by design: the append separately confirms old content
    via the runs.json byte-prefix sha / head-fragment checks, and every
    sampled stat is captured BEFORE the tail parse (same fail-safe
    direction as the full snapshot)."""
    dir_mtime_ns = os.stat(corpus_dir).st_mtime_ns
    entries: list[tuple] = []
    runs_json: list[int] | None = None
    with os.scandir(corpus_dir) as it:
        for entry in it:
            name = entry.name
            if name == index_file:
                st = entry.stat()
                runs_json = [st.st_size, st.st_mtime_ns]
                continue
            if not entry.is_file(follow_symlinks=True):
                continue
            idx = ""
            if name.startswith("run_"):
                cut = name.find("_", 4)
                idx = name[4:cut] if cut > 4 else ""
            # New-run files get stats (their segment's source_fp); so do
            # repair-candidate positions (``extra_positions`` — the
            # quarantine records being re-ingested need fresh per-file
            # fingerprints, ISSUE 9).
            if idx.isdigit() and (
                int(idx) >= n_old
                or (extra_positions and int(idx) in extra_positions)
            ):
                st = entry.stat()
                entries.append((name, st.st_size, st.st_mtime_ns))
            else:
                entries.append((name, None, None))
    # Same selection RULE as the full snapshot (_select_sample), applied to
    # this directory's whole entry list — which includes the new-run files
    # the full path's old+other base excludes, so membership can differ
    # from a from-scratch snapshot's.  Benign: the stored sample is
    # self-contained (name, size, mtime triples), the load-side check
    # compares exactly the stored members.  Stat them now, pre-parse.
    sample = _select_sample(entries)
    sampled: list[list] = []
    for name, size, mtime_ns in sample:
        if size is None:
            st = os.stat(os.path.join(corpus_dir, name))
            size, mtime_ns = st.st_size, st.st_mtime_ns
        sampled.append([name, size, mtime_ns])
    return {
        "dir_mtime_ns": dir_mtime_ns,
        "runs_json": runs_json,
        "index_file": index_file,
        "entries": entries,
        "with_stats": False,
        "sample": sampled,
        "runs_prefix_sha": _runs_prefix_sha(
            corpus_dir, (runs_json or [0])[0], index_file
        ),
    }


def source_from_snapshot(snap: dict, n_old: int, exclude: set | None = None) -> dict:
    """Snapshot -> fingerprint dict, classed so GROWN (runs appended by an
    incremental sweep) is distinguishable from STALE (anything else
    changed):

      * ``old_*``   run_<i>_* files with i < n_old
      * ``new_*``   run_<i>_* files with i >= n_old (normally none at
                    write time)
      * ``other_*`` every other regular file except runs.json
      * ``runs_json`` (size, mtime_ns) of runs.json itself — it
                    legitimately changes on append, so it is compared
                    separately

    Per class both a stat fingerprint (``*_fp``, names+sizes+mtimes; only
    when the snapshot carried stats) and a names-only fingerprint
    (``*_names_fp``) are produced; ``sample`` is a deterministic
    <=:data:`_SAMPLE_FILES` spread of (name, size, mtime_ns) triples over
    the old+other classes for the fast load check.

    ``exclude`` (ISSUE 9) removes QUARANTINED runs' files from every class
    and from the sample: their stats legitimately change when an operator
    repairs them, and that change must classify as GROWN (re-ingest the
    repaired runs via the append path), not STALE.  The excluded files are
    fingerprinted separately, per quarantine record, in the store header."""
    exclude = exclude or frozenset()
    classes: dict[str, list] = {"old": [], "new": [], "other": []}
    old, new, other = classes["old"], classes["new"], classes["other"]
    for rec in snap["entries"]:
        name = rec[0]
        if name in exclude:
            continue
        # Hand-rolled ^run_(\d+)_ classification: the regex engine costs
        # ~1 µs/name, and a 10x corpus directory holds 300k+ entries.
        if name.startswith("run_"):
            cut = name.find("_", 4)
            idx = name[4:cut] if cut > 4 else ""
            if idx.isdigit():
                (old if int(idx) < n_old else new).append(rec)
            else:
                other.append(rec)
        else:
            other.append(rec)

    with_stats = snap.get("with_stats", True)
    out: dict = {
        "runs_json": snap["runs_json"],
        "n_new_files": len(new),
        "dir_mtime_ns": snap["dir_mtime_ns"],
        "n_runs": n_old,
        "runs_prefix_sha": snap.get("runs_prefix_sha"),
    }
    # Non-default index files (trace.json) are recorded so classification
    # and the append dispatch stay injector-agnostic; the Molly default is
    # omitted to keep legacy headers byte-compatible.
    if (snap.get("index_file") or "runs.json") != "runs.json":
        out["index_file"] = snap["index_file"]
    for cls, recs in classes.items():
        out[f"{cls}_names_fp"] = _fp([n for n, _, _ in recs])
        if with_stats:
            out[f"{cls}_fp"] = _fp([f"{n}\0{s}\0{t}" for n, s, t in recs])
    if with_stats:
        out["sample"] = [list(rec) for rec in _select_sample(old + other)]
    elif snap.get("sample") is not None:
        # Partial append snapshot (snapshot_source_appended): the sample
        # was selected and statted at snapshot time, pre-parse.  Excluded
        # (quarantined) files are filtered here too — their repair must
        # not fail the sample check.
        out["sample"] = [
            list(rec) for rec in snap["sample"] if rec[0] not in exclude
        ]
    return out


def scan_source(
    corpus_dir: str,
    n_old: int,
    with_stats: bool = True,
    exclude: set | None = None,
    index_file: str = "runs.json",
) -> dict:
    """One-shot snapshot + classification (the load-side compare path)."""
    return source_from_snapshot(
        snapshot_source(corpus_dir, with_stats, index_file=index_file),
        n_old,
        exclude=exclude,
    )


def _runs_prefix_sha(
    corpus_dir: str, nbytes: int, index_file: str = "runs.json"
) -> str | None:
    """SHA-256 of the index file's first ``nbytes - 1`` bytes: an append
    that re-serializes the same old entries plus new ones keeps this prefix
    when the producer's serializer is stable — the strong old-entry check
    the runs.json append path prefers over the cheap iteration/status
    comparison.  (Single-document layouts wrap their runs in a JSON object
    whose tail rewrites on growth, so their append path never trusts it.)"""
    try:
        sha = hashlib.sha256()
        remaining = max(0, nbytes - 1)
        with open(os.path.join(corpus_dir, index_file), "rb") as fh:
            while remaining:
                chunk = fh.read(min(1 << 20, remaining))
                if not chunk:
                    return None
                sha.update(chunk)
                remaining -= len(chunk)
        return sha.hexdigest()
    except OSError:
        return None


HIT, GROWN, STALE = "hit", "grown", "stale"


def segment_source_fp(snapshot: dict, lo: int, hi: int) -> str:
    """Fingerprint of the SOURCE files belonging to run positions
    [lo, hi) — the ``run_<pos>_*`` files (provenance JSON, spacetime DOTs,
    anything else per-run), names + stats when the snapshot carried them.
    Stored per segment so the analysis result cache (store/rcache.py) can
    key per-segment partials on content the packed arrays do NOT mirror
    (the hazard figures read run_<pos>_spacetime.dot directly)."""
    lines = []
    for rec in snapshot["entries"]:
        name = rec[0]
        if not name.startswith("run_"):
            continue
        cut = name.find("_", 4)
        idx = name[4:cut] if cut > 4 else ""
        if idx.isdigit() and lo <= int(idx) < hi:
            lines.append(f"{rec[0]}\0{rec[1]}\0{rec[2]}")
    return _fp(lines)


def segment_source_fp_positions(snapshot: dict, positions) -> str:
    """:func:`segment_source_fp` over an explicit POSITION SET instead of a
    contiguous range — the quarantine-repair append path's segments carry
    non-contiguous source positions (ISSUE 9)."""
    want = {int(p) for p in positions}
    lines = []
    for rec in snapshot["entries"]:
        name = rec[0]
        if not name.startswith("run_"):
            continue
        cut = name.find("_", 4)
        idx = name[4:cut] if cut > 4 else ""
        if idx.isdigit() and int(idx) in want:
            lines.append(f"{rec[0]}\0{rec[1]}\0{rec[2]}")
    return _fp(lines)


# ---------------------------------------------------------------------------
# quarantine bookkeeping (ISSUE 9)
# ---------------------------------------------------------------------------


def quarantine_file_names(qrecs) -> set:
    """Every file name owned by the header's quarantine records."""
    return {f[0] for rec in qrecs or () for f in rec.get("files") or ()}


def quarantine_files_from_snapshot(snap: dict, position: int) -> list:
    """All ``run_<position>_*`` files of one quarantined position, with the
    snapshot's stats — the per-record fingerprint a repair is detected by.
    Every file of the position is watched (not just the one that failed to
    parse): a repair tool typically rewrites the whole run."""
    out = []
    prefix = f"run_{position}_"
    for rec in snap["entries"]:
        if rec[0].startswith(prefix):
            out.append([rec[0], rec[1], rec[2]])
    return sorted(out)


def quarantine_changed(corpus_dir: str, qrecs) -> list:
    """The quarantine records whose watched files' stats changed on disk —
    repair candidates for the GROWN append path.  A record with no watched
    files (the failure was a runs.json ENTRY, whose repair is caught by the
    runs.json stat / prefix sha instead) never matches here."""
    changed = []
    for rec in qrecs or ():
        files = rec.get("files") or ()
        if not files:
            continue
        for name, size, mtime_ns in files:
            try:
                st = os.stat(os.path.join(corpus_dir, name))
            except OSError:
                changed.append(rec)
                break
            if st.st_size != size or st.st_mtime_ns != mtime_ns:
                changed.append(rec)
                break
    return changed


def stored_positions(header: dict) -> list[int]:
    """Stored row -> source position, across all segments in append order.
    Segments written before quarantine support (no ``positions`` key) are
    contiguous from the first position after every earlier segment."""
    out: list[int] = []
    nxt = 0
    for seg in header["segments"]:
        pos = seg.get("positions")
        if pos is None:
            pos = range(nxt, nxt + int(seg["n_runs"]))
        out.extend(int(p) for p in pos)
        nxt = (max(out) + 1) if out else 0
    return out


def segment_fingerprint(entry: dict) -> str:
    """Content address of one store segment: its packed-shard checksums,
    its shape row, and its source-file fingerprint.  The analysis result
    cache keys every per-segment partial (and, joined over all segments,
    every full report) on exactly this."""
    doc = [
        int(entry["n_runs"]),
        int(entry["v"]),
        int(entry["e"]),
        int(entry["max_depth"]),
        entry.get("source_fp") or "",
        sorted((m["file"], m["sha256"]) for m in entry["shards"]),
    ]
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


def _sample_ok(corpus_dir: str, sample: list) -> bool:
    for name, size, mtime_ns in sample or ():
        try:
            st = os.stat(os.path.join(corpus_dir, name))
        except OSError:
            return False
        if st.st_size != size or st.st_mtime_ns != mtime_ns:
            return False
    return True


def classify_source(header: dict, corpus_dir: str) -> str:
    """HIT (byte-trustworthy), GROWN (append candidate), or STALE.

    ``fast`` mode (default, :func:`fingerprint_mode`) compares names-only
    fingerprints plus runs.json's stat plus the stored stat sample — one
    scandir and <=~65 stats regardless of corpus size.  ``full`` mode
    re-stats every file and compares the exhaustive fingerprints.

    Quarantined runs' files (ISSUE 9) are excluded from every class
    fingerprint and statted individually instead: unchanged -> the store
    still serves (same healthy rows, same quarantine list); changed (the
    operator repaired a run) -> GROWN, so the append path re-ingests
    exactly the repaired positions."""
    src = header.get("source") or {}
    index_file = src.get("index_file") or "runs.json"
    qrecs = header.get("quarantined") or ()
    qnames = quarantine_file_names(qrecs)
    full = fingerprint_mode() == "full"
    if not full and src.get("dir_mtime_ns"):
        # Tier 0, no directory enumeration at all: entry creates/deletes/
        # renames bump the dir mtime, so an unchanged dir mtime + unchanged
        # runs.json + intact stat sample is a HIT in ~66 stats regardless
        # of corpus size (a 10x directory holds 300k+ entries; even
        # enumerating names costs more than the whole mmap load).
        try:
            st = os.stat(corpus_dir)
            # The index file is whichever one the store was populated
            # against (ingest/adapters.py seam; legacy headers default to
            # Molly's runs.json).  A snapshot that saw no index at all
            # recorded None; one appearing later bumps the dir mtime, so
            # tier 0 falls through to the scan below.
            rj = (
                os.stat(os.path.join(corpus_dir, index_file))
                if src.get("runs_json") is not None
                else None
            )
        except OSError:
            return STALE
        cur_rj = [rj.st_size, rj.st_mtime_ns] if rj is not None else None
        if (
            st.st_mtime_ns == src["dir_mtime_ns"]
            and cur_rj == src.get("runs_json")
            and _sample_ok(corpus_dir, src.get("sample"))
        ):
            # An in-place repair of a quarantined file bumps neither the
            # dir mtime nor runs.json — its bounded per-record stat check
            # is the only tripwire at tier 0.
            if qrecs and quarantine_changed(corpus_dir, qrecs):
                return GROWN
            return HIT
        # Something moved: fall through to the name-level scan to tell
        # GROWN from STALE.
    cur = scan_source(
        corpus_dir,
        int(src.get("n_runs", 0)),
        with_stats=full,
        exclude=qnames,
        index_file=index_file,
    )
    if full:
        base_ok = cur["old_fp"] == src.get("old_fp") and cur["other_fp"] == src.get(
            "other_fp"
        )
        hit_ok = base_ok and cur["new_fp"] == src.get("new_fp")
    else:
        base_ok = (
            cur["old_names_fp"] == src.get("old_names_fp")
            and cur["other_names_fp"] == src.get("other_names_fp")
            and _sample_ok(corpus_dir, src.get("sample"))
        )
        hit_ok = base_ok and cur["new_names_fp"] == src.get("new_names_fp")
    if not base_ok:
        return STALE
    if hit_ok and cur["runs_json"] == src.get("runs_json"):
        # Healthy classes intact; a repaired quarantined run is the GROWN
        # (re-ingest) case, an untouched quarantine set a plain HIT.
        if qrecs and quarantine_changed(corpus_dir, qrecs):
            return GROWN
        return HIT
    # Append candidate: every stored file untouched, runs.json changed, new
    # run files exist, and the store was written with none pending (a store
    # written over stray future-run files cannot tell them apart — rebuild).
    if (
        cur["n_new_files"] > 0
        and int(src.get("n_new_files", 0)) == 0
        and cur["runs_json"] != src.get("runs_json")
    ):
        return GROWN
    # Single-document layouts (trace.json): growth happens INSIDE the index
    # file and no per-run files ever appear, so an index-only change with
    # every other file intact is the append candidate — the append path
    # re-verifies the old entries before trusting it (and refuses, loudly,
    # when they moved, which downgrades to the full reparse).
    if (
        index_file != "runs.json"
        and cur["n_new_files"] == 0
        and int(src.get("n_new_files", 0)) == 0
        and cur["runs_json"] != src.get("runs_json")
    ):
        return GROWN
    return STALE


# ---------------------------------------------------------------------------
# segment payloads (what a writer persists)
# ---------------------------------------------------------------------------


@dataclass
class SegmentPayload:
    """One segment's full content, producer-agnostic.  ``prov`` /
    ``node_ids`` / ``heads`` are callables so the native producer can fetch
    C++-held strings lazily inside the parallel shard writers instead of
    materializing the whole corpus's serialization up front."""

    n_runs: int
    v: int
    e: int
    max_depth: int
    pre: object  # NativeCondBatch-shaped (12 arrays)
    post: object
    iteration: np.ndarray
    success: np.ndarray
    statuses: list[bytes]
    holds_pre: list[bytes]  # per-run JSON array of holds-map keys
    holds_post: list[bytes]
    head: object  # row -> bytes
    prov: object  # (cond_name, row) -> bytes
    node_ids: object  # (cond_name, row) -> bytes ("\n"-joined)
    #: the vocabulary these arrays were encoded against (CorpusVocab or
    #: {part: list[str]} dict) — persisted alongside the segment
    vocab: object = None


def _int32_checked(values, what: str) -> np.ndarray:
    arr = np.asarray(values, dtype=np.int64)
    if arr.size and (arr.max(initial=0) > 2**31 - 1 or arr.min(initial=0) < -(2**31)):
        raise ValueError(f"{what} out of int32 range")
    return arr.astype(np.int32)


def payload_from_packed_molly(molly) -> SegmentPayload:
    """Native producer: a MollyOutput from load_molly_output_packed — the
    arrays come straight from the C++ corpus, the strings from its live
    handle (parse-time canonical serializations)."""
    nc = molly.native_corpus
    runs = molly.runs
    return SegmentPayload(
        n_runs=nc.n_runs,
        v=nc.v,
        e=nc.e,
        max_depth=nc.max_depth,
        pre=nc.pre,
        post=nc.post,
        iteration=np.asarray(nc.iteration, dtype=np.int32),
        success=np.asarray(nc.success, dtype=bool),
        statuses=[r.status.encode() for r in runs],
        holds_pre=[json.dumps(list(r.time_pre_holds)).encode() for r in runs],
        holds_post=[json.dumps(list(r.time_post_holds)).encode() for r in runs],
        head=nc.run_head_json,
        prov=nc.prov_json,
        node_ids=lambda c, i: "\n".join(nc.lazy_node_ids(c, i)).encode(),
        vocab={"tables": nc.tables, "labels": nc.labels, "times": nc.times},
    )


def _head_bytes(run) -> bytes:
    """The canonical debugging.json head fragment — byte-identical to the
    C++ engine's build_run_head and to analysis/pipeline._run_json_str's
    object-path rendering (same pairs, same json.dumps defaults)."""
    return (
        f'"iteration": {json.dumps(run.iteration)}, '
        f'"status": {json.dumps(run.status)}, '
        f'"failureSpec": {json.dumps(run.failure_spec.to_json() if run.failure_spec else None)}, '
        f'"model": {json.dumps(run.model.to_json() if run.model else None)}, '
        f'"messages": {json.dumps([m.to_json() for m in run.messages])}'
    ).encode()


def _chain_linear_one(g) -> bool:
    """Per-graph @next-chain linearity over one PackedGraph — the Python
    mirror of the native parse-time graph_chain_linear, via the batched host
    check restricted to a single row."""
    from nemo_tpu.ops.simplify import chains_linear_host

    n = g.n_nodes
    is_goal = np.zeros((1, max(1, n)), dtype=bool)
    is_goal[0, : g.n_goals] = True
    node_mask = np.zeros((1, max(1, n)), dtype=bool)
    node_mask[0, :n] = True
    type_id = np.zeros((1, max(1, n)), dtype=np.int32)
    type_id[0, :n] = g.type_id
    ne = len(g.edges)
    src = g.edges[:, 0].reshape(1, -1) if ne else np.zeros((1, 0), np.int32)
    dst = g.edges[:, 1].reshape(1, -1) if ne else np.zeros((1, 0), np.int32)
    em = np.ones((1, ne), dtype=bool)
    return bool(chains_linear_host(is_goal, node_mask, type_id, src, dst, em))


def payload_from_runs(runs: list, vocab) -> SegmentPayload:
    """Pure-Python producer: pack RunData objects (object-loader provenance)
    into a segment against ``vocab`` (a CorpusVocab — pass a fresh one for a
    full store, the store's interned one for an append, which extends it
    in place).  Interning order matches the native engine: all pre graphs
    in run order, then all post."""
    from nemo_tpu.graphs.packed import bucket_size, longest_path_len, pack_graph

    pre_g = [pack_graph(r.pre_prov, vocab) for r in runs]
    post_g = [pack_graph(r.post_prov, vocab) for r in runs]
    all_g = pre_g + post_g
    v = bucket_size(max((g.n_nodes for g in all_g), default=1))
    e = bucket_size(max((len(g.edges) for g in all_g), default=1))
    max_lp = max((longest_path_len(g.n_nodes, g.edges) for g in all_g), default=0)
    b = len(runs)

    def pack_cond(graphs):
        """Mirror of the native pack_cond fills (table/label/time -1, type 0,
        edges 0, masks False)."""
        from nemo_tpu.ingest.native import NativeCondBatch

        out = dict(
            table_id=np.full((b, v), -1, np.int32),
            label_id=np.full((b, v), -1, np.int32),
            time_id=np.full((b, v), -1, np.int32),
            type_id=np.zeros((b, v), np.int32),
            is_goal=np.zeros((b, v), bool),
            node_mask=np.zeros((b, v), bool),
            edge_src=np.zeros((b, e), np.int32),
            edge_dst=np.zeros((b, e), np.int32),
            edge_mask=np.zeros((b, e), bool),
            n_nodes=np.zeros(b, np.int32),
            n_goals=np.zeros(b, np.int32),
            chain_linear=np.zeros(b, bool),
        )
        for i, g in enumerate(graphs):
            n = g.n_nodes
            out["n_nodes"][i] = n
            out["n_goals"][i] = g.n_goals
            out["table_id"][i, :n] = g.table_id
            out["label_id"][i, :n] = g.label_id
            out["time_id"][i, :n] = g.time_id
            out["type_id"][i, :n] = g.type_id
            out["is_goal"][i, : g.n_goals] = True
            out["node_mask"][i, :n] = True
            ne = len(g.edges)
            if ne:
                out["edge_src"][i, :ne] = g.edges[:, 0]
                out["edge_dst"][i, :ne] = g.edges[:, 1]
                out["edge_mask"][i, :ne] = True
            out["chain_linear"][i] = _chain_linear_one(g)
        return NativeCondBatch(**out)

    graphs_by_cond = {"pre": pre_g, "post": post_g}
    # Holds-map keying matches ingest/molly.py:attach_run_metadata exactly
    # ({row[-1]: True ...} — dedup keeps first-occurrence order).
    def holds_keys(run, cond: str) -> bytes:
        tables = run.model.tables if run.model else {}
        return json.dumps(
            list({row[-1]: True for row in tables.get(cond, []) if row})
        ).encode()

    return SegmentPayload(
        n_runs=b,
        v=v,
        e=e,
        max_depth=min(v, max(1, max_lp + 1)),
        pre=pack_cond(pre_g),
        post=pack_cond(post_g),
        iteration=_int32_checked([r.iteration for r in runs], "run iteration"),
        success=np.asarray([r.succeeded for r in runs], dtype=bool),
        statuses=[r.status.encode() for r in runs],
        holds_pre=[holds_keys(r, "pre") for r in runs],
        holds_post=[holds_keys(r, "post") for r in runs],
        head=lambda i: _head_bytes(runs[i]),
        prov=lambda c, i: json.dumps(
            (runs[i].pre_prov if c == "pre" else runs[i].post_prov).to_json()
        ).encode(),
        node_ids=lambda c, i: "\n".join(graphs_by_cond[c][i].node_ids).encode(),
        vocab=vocab,
    )


def payload_from_molly(molly) -> SegmentPayload:
    """Producer dispatch: packed-first MollyOutputs persist their native
    corpus verbatim; object-loader MollyOutputs pack in Python.  Both yield
    bit-compatible stores (the two ETLs are bit-identical by contract,
    tests/test_native.py)."""
    if getattr(molly, "native_corpus", None) is not None:
        return payload_from_packed_molly(molly)
    from nemo_tpu.graphs.packed import CorpusVocab

    return payload_from_runs(list(molly.runs), CorpusVocab())


# ---------------------------------------------------------------------------
# segment writing (parallel shards)
# ---------------------------------------------------------------------------


def _string_chunk_rows(b: int, workers: int) -> int:
    return max(256, -(-b // max(1, workers * 2)))


def write_segment(seg_dir: str, payload: SegmentPayload, workers: int) -> dict:
    """Write one segment directory; returns its header entry.  Shards are
    written in parallel by a thread pool: one shard per array group plus
    row-chunked string shards per condition, so a big corpus's serialization
    and hashing spread across cores (writes + hashlib/zlib release the GIL,
    and the array payloads are shared memory — no pickling)."""
    from concurrent.futures import ThreadPoolExecutor

    os.makedirs(seg_dir, exist_ok=True)
    b = payload.n_runs
    chunk = _string_chunk_rows(b, workers)
    jobs: list[tuple[str, object]] = []

    def cond_regions(cond):
        return lambda: [(name, getattr(cond, name)) for name, _ in _COND_ARRAYS]

    jobs.append(("arrays_pre.bin", cond_regions(payload.pre)))
    jobs.append(("arrays_post.bin", cond_regions(payload.post)))
    jobs.append(
        (
            "runs.bin",
            lambda: [
                ("iteration", payload.iteration),
                ("success", np.asarray(payload.success, dtype=bool)),
            ],
        )
    )
    jobs.append(
        (
            "meta.bin",
            lambda: (
                _blob_regions("status", payload.statuses)
                + _blob_regions("holds_pre", payload.holds_pre)
                + _blob_regions("holds_post", payload.holds_post)
                + _blob_regions("head", [payload.head(i) for i in range(b)])
            ),
        )
    )

    def string_shard(cond_name: str, start: int, end: int):
        def build():
            prov = [payload.prov(cond_name, i) for i in range(start, end)]
            ids = [payload.node_ids(cond_name, i) for i in range(start, end)]
            return _blob_regions("prov", prov) + _blob_regions("node_ids", ids)

        return build

    for cond_name in ("pre", "post"):
        for k, start in enumerate(range(0, b, chunk)):
            jobs.append(
                (
                    f"strings_{cond_name}_{k:03d}.bin",
                    string_shard(cond_name, start, min(b, start + chunk)),
                )
            )

    def run_job(job):
        fname, regions = job
        return write_shard(os.path.join(seg_dir, fname), regions())

    if workers > 1:
        with ThreadPoolExecutor(max_workers=workers) as pool:
            manifests = list(pool.map(run_job, jobs))
    else:
        manifests = [run_job(j) for j in jobs]
    return {
        "name": os.path.basename(seg_dir),
        "n_runs": b,
        "v": payload.v,
        "e": payload.e,
        "max_depth": payload.max_depth,
        "string_chunk_rows": chunk,
        "shards": manifests,
    }


def write_vocab(path: str, vocab) -> dict:
    """tables/labels/times blobs (CorpusVocab or plain string lists)."""
    def strings(part):
        v = getattr(vocab, part)
        return getattr(v, "strings", v)

    regions = []
    for part in ("tables", "labels", "times"):
        regions += _blob_regions(part, [s.encode() for s in strings(part)])
    return write_shard(path, regions)
