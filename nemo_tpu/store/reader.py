"""Warm-load side of the ``.npack`` store: mmap shards -> packed MollyOutput.

The loaded object is bit-interchangeable with the packed-first loader's
(ingest/native.py:load_molly_output_packed): runs carry RawProv placeholders
whose ``json_str()`` splices the stored parse-time serialization, LazyRunData
head fragments come from the stored head blob, and ``.native_corpus`` exposes
the packed arrays (memmapped, read-only) for the JaxBackend's zero-repack
init path.  The run-metadata trio (failureSpec/model/messages) materializes
from the ORIGINAL runs.json lazily — the standard pipeline never touches it,
so a warm load never parses runs.json at all.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np

from nemo_tpu.store.npack import ShardReader, StoreCorrupt

#: Padding values per cond-array (the native pack_cond fills) — used when a
#: multi-segment store consolidates differently-bucketed segments.
_PAD = {
    "table_id": -1,
    "label_id": -1,
    "time_id": -1,
    "type_id": 0,
    "is_goal": False,
    "node_mask": False,
    "edge_src": 0,
    "edge_dst": 0,
    "edge_mask": False,
}


class LazyCondBatch:
    """Multi-segment NativeCondBatch stand-in whose big ``[B,V]``/``[B,E]``
    planes consolidate LAZILY (ISSUE 12): the ``b``-kind per-run vectors
    (n_nodes/n_goals/chain_linear — what sizing, the giant split, and the
    linear fast-path gate read) concatenate eagerly at mmap cost, but a
    corpus-wide pad+concat of the node/edge planes only happens on the
    first attribute access — so a report-only touch (string splicing, run
    metadata) of a multi-segment store never materializes them at all, and
    the streamed analysis path reads row subsets through :meth:`take`
    against the per-segment mmaps, keeping peak memory O(segment) instead
    of O(corpus).  An attribute access materializes the full plane exactly
    once (cached on the instance), byte-identical to the eager
    consolidation it replaces."""

    def __init__(self, cond: str, seg_readers: list[dict], segs: list[dict]) -> None:
        from nemo_tpu.store.npack import _COND_ARRAYS

        self._cond = cond
        self._readers = seg_readers
        self._segs = segs
        self._v = max(int(s["v"]) for s in segs)
        self._e = max(int(s["e"]) for s in segs)
        self._kind = dict(_COND_ARRAYS)
        seg_runs = [int(s["n_runs"]) for s in segs]
        self._b = sum(seg_runs)
        self._starts = np.cumsum([0] + seg_runs)
        for name, kind in _COND_ARRAYS:
            if kind == "b":
                setattr(
                    self,
                    name,
                    np.concatenate(
                        [self._region(k, name) for k in range(len(segs))]
                    ),
                )

    def _region(self, k: int, name: str) -> np.ndarray:
        return self._readers[k][f"arrays_{self._cond}.bin"].region(name)

    def _width(self, kind: str) -> int:
        return self._v if kind == "bv" else self._e

    def __getattr__(self, name: str) -> np.ndarray:
        # Only reached when the attribute is NOT yet set: the full lazy
        # consolidation, cached via setattr so later reads are plain.
        kind = self.__dict__.get("_kind", {}).get(name)
        if kind is None or kind == "b":
            raise AttributeError(name)
        parts = [self._region(k, name) for k in range(len(self._segs))]
        out = np.full(
            (self._b, self._width(kind)), _PAD[name], dtype=parts[0].dtype
        )
        row = 0
        for p in parts:
            out[row : row + p.shape[0], : p.shape[1]] = p
            row += p.shape[0]
        setattr(self, name, out)
        return out

    def take(self, name: str, rows) -> np.ndarray:
        """Gather ``rows`` (global positions, any order) of one plane from
        the per-segment mmaps, padded to the consolidated width — the values
        the eager path's ``consolidated[rows]`` would produce, without ever
        materializing the corpus-wide plane.  Reads only the touched
        segments' pages."""
        kind = self._kind[name]
        idx = np.asarray(rows, dtype=np.int64)
        if kind == "b":
            return getattr(self, name)[idx]
        if name in self.__dict__:  # already consolidated — use it
            return self.__dict__[name][idx]
        seg_of = np.searchsorted(self._starts, idx, side="right") - 1
        out = np.full(
            (len(idx), self._width(kind)),
            _PAD[name],
            dtype=self._region(0, name).dtype,
        )
        for k in np.unique(seg_of):
            sel = np.nonzero(seg_of == k)[0]
            src = self._region(int(k), name)
            out[sel, : src.shape[1]] = src[idx[sel] - int(self._starts[k])]
        return out


class _SegmentStrings:
    """String access for one segment: the meta shard's status/holds/head
    blobs plus the row-chunked prov/node-id shards per condition."""

    def __init__(self, entry: dict, readers: dict) -> None:
        self.chunk = int(entry["string_chunk_rows"])
        self.meta = readers["meta.bin"]
        self.head = self.meta.blob("head")
        self._chunks = {"pre": [], "post": []}
        for cond in ("pre", "post"):
            k = 0
            while f"strings_{cond}_{k:03d}.bin" in readers:
                self._chunks[cond].append(readers[f"strings_{cond}_{k:03d}.bin"])
                k += 1

    def _blob(self, cond: str, row: int, name: str) -> bytes:
        rd = self._chunks[cond][row // self.chunk]
        return rd.blob(name).row(row % self.chunk)

    def prov(self, cond: str, row: int) -> bytes:
        return self._blob(cond, row, "prov")

    def node_ids(self, cond: str, row: int) -> bytes:
        return self._blob(cond, row, "node_ids")


class StoreStrings:
    """Global-row string accessors over all segments."""

    def __init__(self, segments: list[_SegmentStrings], seg_runs: list[int]) -> None:
        self.segments = segments
        self.starts = np.cumsum([0] + seg_runs)

    def _locate(self, row: int) -> tuple[_SegmentStrings, int]:
        s = int(np.searchsorted(self.starts, row, side="right")) - 1
        return self.segments[s], row - int(self.starts[s])

    def prov(self, cond: str, row: int) -> bytes:
        seg, r = self._locate(row)
        return seg.prov(cond, r)

    def node_ids(self, cond: str, row: int) -> bytes:
        seg, r = self._locate(row)
        return seg.node_ids(cond, r)

    def head(self, row: int) -> bytes:
        seg, r = self._locate(row)
        return seg.head.row(r)


def _import_native():
    # One import site: the reader builds the exact types the packed-first
    # loader builds, so downstream (backend, report splicing) cannot drift.
    from nemo_tpu.ingest.native import LazyRunData, NativeCondBatch, NativeCorpus, RawProv

    return LazyRunData, NativeCondBatch, NativeCorpus, RawProv


def _store_corpus_cls():
    LazyRunData, NativeCondBatch, NativeCorpus, RawProv = _import_native()

    @dataclass
    class StoreCorpus(NativeCorpus):
        """NativeCorpus whose per-run strings come from store blobs instead
        of a live C++ handle.  The arrays are memmaps (single segment,
        zero-copy) or consolidated numpy (multi-segment)."""

        strings: StoreStrings | None = None

        def prov_json(self, cond_name: str, row: int) -> bytes:
            out = self.strings.prov(cond_name, row)
            if not out:
                raise StoreCorrupt(
                    f"empty stored provenance for cond {cond_name} run row {row}"
                )
            return out

        def run_head_json(self, row: int) -> bytes:
            out = self.strings.head(row)
            if not out:
                raise StoreCorrupt(f"empty stored head fragment for run row {row}")
            return out

        def lazy_node_ids(self, cond_name: str, row: int) -> list[str]:
            joined = self.strings.node_ids(cond_name, row).decode()
            return joined.split("\n") if joined else []

    return StoreCorpus


class _RawRuns:
    """Shared lazy runs.json parse: the metadata trio of a store-loaded run
    is only reachable through here, and the file is parsed at most once per
    load — and not at all on the standard pipeline path."""

    def __init__(self, path: str, expected_n: int) -> None:
        self.path = path
        self.expected_n = expected_n
        self._rows: list | None = None

    def row(self, i: int) -> dict:
        if self._rows is None:
            with open(self.path, "r", encoding="utf-8") as fh:
                self._rows = json.load(fh)
            if len(self._rows) < self.expected_n:
                raise StoreCorrupt(
                    f"{self.path} has {len(self._rows)} runs but the store "
                    f"holds {self.expected_n}"
                )
        return self._rows[i]


class _HeadRuns:
    """Raw-entry source for layouts WITHOUT a runs.json (the non-Molly
    ingest adapters, ingest/adapters.py): the lazy metadata trio parses the
    STORED head fragment instead — the same five canonical pairs
    (iteration/status/failureSpec/model/messages) the populate serialized,
    so the materialized objects equal the cold parse's.  Indexed by SOURCE
    position like :class:`_RawRuns` (the proxy's contract); quarantine
    stores map position -> stored row via ``positions``."""

    def __init__(self, corpus, positions: list[int] | None) -> None:
        self._corpus = corpus
        self._row_of = (
            {int(p): r for r, p in enumerate(positions)} if positions else None
        )

    def row(self, i: int) -> dict:
        row = self._row_of[i] if self._row_of is not None else i
        return json.loads(b"{" + self._corpus.run_head_json(row) + b"}")


class _RawProxy:
    """dict-shaped view of one run's runs.json entry, parsed on demand."""

    __slots__ = ("_runs", "_i")

    def __init__(self, runs: _RawRuns, i: int) -> None:
        self._runs = runs
        self._i = i

    def get(self, key, default=None):
        return self._runs.row(self._i).get(key, default)

    def __getitem__(self, key):
        return self._runs.row(self._i)[key]


def open_segments(store_dir: str, header: dict, verify: bool) -> tuple:
    """mmap every shard of every segment (verifying checksums when asked);
    returns (per-segment reader dicts, vocab reader, total mapped bytes)."""
    seg_readers: list[dict[str, ShardReader]] = []
    total = 0
    for entry in header["segments"]:
        readers: dict[str, ShardReader] = {}
        for manifest in entry["shards"]:
            path = os.path.join(store_dir, entry["name"], manifest["file"])
            rd = ShardReader(path, manifest)
            if verify:
                rd.verify()
            readers[manifest["file"]] = rd
            total += rd.nbytes
        seg_readers.append(readers)
    vpath = os.path.join(store_dir, header["vocab_shard"]["file"])
    vocab_rd = ShardReader(vpath, header["vocab_shard"])
    if verify:
        vocab_rd.verify()
    total += vocab_rd.nbytes
    return seg_readers, vocab_rd, total


def _decode_vocab(vocab_rd: ShardReader, part: str) -> list[str]:
    blob = vocab_rd.blob(part)
    return [blob.row(i).decode() for i in range(len(blob))]


def build_corpus(store_dir: str, header: dict, seg_readers: list[dict], vocab_rd):
    """Assemble the StoreCorpus from mmapped shards.  Single segment: every
    array is a zero-copy memmap view.  Multiple segments: a
    :class:`LazyCondBatch` — per-run vectors consolidated eagerly, the big
    node/edge planes consolidated only on first touch (byte-identical to
    the old eager pad+concat) and row-gatherable per segment via
    ``take()`` (the streamed path's bounded-working-set read)."""
    _, NativeCondBatch, _, _ = _import_native()
    from nemo_tpu.store.npack import _COND_ARRAYS

    segs = header["segments"]
    seg_runs = [int(s["n_runs"]) for s in segs]

    def cond_batch(cond: str):
        if len(segs) == 1:
            rd = seg_readers[0][f"arrays_{cond}.bin"]
            return NativeCondBatch(**{n: rd.region(n) for n, _ in _COND_ARRAYS})
        return LazyCondBatch(cond, seg_readers, segs)

    iteration = (
        seg_readers[0]["runs.bin"].region("iteration")
        if len(segs) == 1
        else np.concatenate([sr["runs.bin"].region("iteration") for sr in seg_readers])
    )
    success = (
        seg_readers[0]["runs.bin"].region("success")
        if len(segs) == 1
        else np.concatenate([sr["runs.bin"].region("success") for sr in seg_readers])
    )
    strings = StoreStrings(
        [_SegmentStrings(s, rd) for s, rd in zip(segs, seg_readers)], seg_runs
    )
    StoreCorpus = _store_corpus_cls()
    return StoreCorpus(
        n_runs=sum(seg_runs),
        v=max(int(s["v"]) for s in segs),
        e=max(int(s["e"]) for s in segs),
        tables=_decode_vocab(vocab_rd, "tables"),
        labels=_decode_vocab(vocab_rd, "labels"),
        times=_decode_vocab(vocab_rd, "times"),
        pre_tid=int(header["pre_tid"]),
        post_tid=int(header["post_tid"]),
        max_depth=max(int(s["max_depth"]) for s in segs),
        iteration=iteration,
        success=success,
        pre=cond_batch("pre"),
        post=cond_batch("post"),
        node_ids_pre=[],
        node_ids_post=[],
        handle=None,
        strings=strings,
    )


_store_run_cls_cache: list = []


def _store_run_cls():
    if _store_run_cls_cache:
        return _store_run_cls_cache[0]
    LazyRunData, _, _, _ = _import_native()

    class StoreRunData(LazyRunData):
        """LazyRunData whose metadata trio parses the original runs.json
        only on attribute access, whose head fragment comes from the store,
        and whose holds maps decode from the store's blobs on first touch.

        Instances are built by :func:`molly_from_corpus` via ``__new__`` +
        a template ``__dict__`` (NOT the dataclass ``__init__`` chain): at
        10x scale the per-run constructor overhead was the warm load's
        dominant Python cost.  The template is produced by the real
        ``RunData()`` constructor, so future dataclass fields keep their
        defaults automatically."""

        def _holds_get(self, cond: str) -> dict:
            h = self._holds
            got = h.get(cond)
            if got is None:
                pre_b, post_b, local = self._holds_blobs
                raw = (pre_b if cond == "pre" else post_b).row(local)
                # Same keying as ingest/molly.py:attach_run_metadata
                # ({row[-1]: True ...}); the key list was deduped in order
                # at store-write time.
                got = h[cond] = dict.fromkeys(json.loads(raw), True)
            return got

        time_pre_holds = property(
            lambda s: s._holds_get("pre"),
            lambda s, v: s._holds.__setitem__("pre", v),
        )
        time_post_holds = property(
            lambda s: s._holds_get("post"),
            lambda s, v: s._holds.__setitem__("post", v),
        )

    _store_run_cls_cache.append(StoreRunData)
    return StoreRunData


def molly_from_corpus(corpus, corpus_dir: str, positions: list[int] | None = None):
    """StoreCorpus -> MollyOutput, mirroring load_molly_output_packed's
    product (RawProv placeholders, lazy head-carrying runs, iteration
    bookkeeping) without touching any source JSON.  The per-run Python work
    is kept near zero — template-dict construction, lazy holds/trio — so a
    warm load stays mmap-bound even at 100k-run scale.

    ``positions`` maps stored row -> SOURCE position (npack.stored_positions
    — identity when omitted): quarantine/repair stores hold a row subset,
    so the lazy runs.json trio (failure_spec/model/messages) must index the
    source file by position, not by row (ISSUE 9)."""
    LazyRunData, _, _, RawProv = _import_native()
    from nemo_tpu.ingest.datatypes import RunData
    from nemo_tpu.ingest.molly import MollyOutput

    StoreRunData = _store_run_cls()
    runs_path = os.path.join(corpus_dir, "runs.json")
    out = MollyOutput(
        run_name=os.path.basename(os.path.normpath(corpus_dir)),
        output_dir=corpus_dir,
        # Molly layouts (runs.json present) ship per-run spacetime DOTs the
        # hazard loop reads from the source dir; other injector layouts
        # synthesize them from message histories (ingest/molly.py).
        ships_spacetime_dots=os.path.exists(runs_path),
    )
    expected_n = (max(positions) + 1) if positions else corpus.n_runs
    # Molly layouts resolve the lazy trio from the source runs.json; other
    # injector layouts (ingest/adapters.py) have none — theirs parses from
    # the stored head fragments, which carry the same five fields.
    raws = (
        _RawRuns(runs_path, expected_n)
        if os.path.exists(runs_path)
        else _HeadRuns(corpus, positions)
    )
    strings = corpus.strings
    # Every RunData default (future fields included), captured once from the
    # real constructor; mutable containers are copied per run below.
    tmpl = RunData().__dict__
    plain = [(k, v) for k, v in tmpl.items() if not isinstance(v, (list, dict))]
    mutable = [(k, v) for k, v in tmpl.items() if isinstance(v, (list, dict))]
    sentinels = {
        "failure_spec": LazyRunData._SENTINEL,
        "model": LazyRunData._SENTINEL,
        "messages": LazyRunData._SENTINEL,
    }
    iters = np.asarray(corpus.iteration)
    iters_list = iters.tolist()  # plain ints: memmap indexing costs ~9 µs/row
    runs = []
    row = 0
    for seg in strings.segments:
        statuses = seg.meta.blob("status").rows()  # one bulk read
        hpre_b = seg.meta.blob("holds_pre")
        hpost_b = seg.meta.blob("holds_post")
        for local in range(len(statuses)):
            d = dict(plain)
            for k, v in mutable:
                d[k] = v.copy()
            d["iteration"] = iters_list[row]
            d["status"] = statuses[local].decode()
            d["_raw"] = _RawProxy(
                raws, positions[row] if positions else row
            )
            d["_lazy"] = dict(sentinels)
            d["_head_corpus"] = corpus
            d["_head_row"] = row
            d["_holds"] = {}
            d["_holds_blobs"] = (hpre_b, hpost_b, local)
            d["pre_prov"] = RawProv(corpus, "pre", row)
            d["post_prov"] = RawProv(corpus, "post", row)
            run = StoreRunData.__new__(StoreRunData)
            run.__dict__ = d
            runs.append(run)
            row += 1
    out.runs = runs
    # Same bookkeeping attach_run_metadata does, vectorized; `success` is
    # the stored exact-"success" classification (molly.go:53).
    succ = np.asarray(corpus.success, dtype=bool)
    out.runs_iters = iters.tolist()
    out.success_runs_iters = iters[succ].tolist()
    out.failed_runs_iters = iters[~succ].tolist()
    out.native_corpus = corpus
    return out
