"""Content-addressed analysis result cache (sibling of the corpus store).

Three entry kinds under one size-capped root (default
``~/.cache/nemo_tpu/results``; ``NEMO_RESULT_CACHE`` / ``--result-cache``
override, ``off`` disables):

  * ``report/<key>/``  — a full report tree (minus the nondeterministic
    telemetry files): a warm repeat request restores it with ZERO kernel
    dispatches and no backend at all;
  * ``partial/<key>/`` — one store segment's :class:`SegmentPartial` JSON
    plus its rendered figure files: a GROWN corpus maps only its new
    segments and merges these (analysis/delta.py);
  * ``blob/<ns>/<key>`` — small opaque payloads (the sidecar's AnalyzeDir
    response cache).

Keys are produced by analysis/delta.py from (store segment fingerprints,
analysis config, kernel/report ABI versions) — pure content addressing, so
the cache needs no invalidation protocol: any input change produces a new
key and the stale entry ages out via the same LRU size-cap machinery the
corpus store uses (``NEMO_RESULT_CACHE_MAX_GB``, last-use stamped on every
hit).  Every entry carries a sha256 manifest; a corrupted entry fails the
verify pass (``NEMO_STORE_VERIFY=off`` skips it, like the store) and is
treated as a loud, counted miss — never served.

Files are hardlinked between the cache and report trees where the
filesystem allows (the report is regenerated output, and a mutated
hardlinked report file is exactly what the manifest verify catches), with
a copy fallback across devices.

**Shared fleet tier (ISSUE 14):** ``NEMO_RCACHE_SHARED`` /
``--shared-cache DIR`` names a SECOND root on a directory every replica
can reach (an NFS/FUSE mount, a shared volume).  Reads consult the local
root first, then the shared one (``rcache.<kind>_shared_hit``); every
local publish replicates to the shared root, so any replica serves any
warm corpus at all three tiers.  Consistency needs no protocol: keys are
pure content addresses, so two replicas racing to publish the same key
produce byte-identical entries — the loser of the fcntl-guarded
check-then-rename is counted (``rcache.publish_race``), never torn.  LRU
last-use stamps (entry.json mtime on every hit) work unchanged on the
shared tier, and both roots share the ``NEMO_RESULT_CACHE_MAX_GB`` cap.

The shared root also hosts the fleet's **leader lease files**
(:class:`Lease`, under ``<shared>/lease/<ns>/``): a cross-replica
single-flight ticket keyed on the same tier-3 content address, with a
heartbeat (mtime refresh) and a TTL (``NEMO_LEASE_TTL_S``) so a dead
leader's followers re-elect instead of waiting forever.  Lease files are
excluded from eviction.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import shutil
import socket as _socket
import time
import uuid
from contextlib import contextmanager

from nemo_tpu import obs
from nemo_tpu.obs import log as obs_log
from nemo_tpu.store.npack import _verify_on_load

_log = obs_log.get_logger("nemo.rcache")


def result_cache_dir(arg: str | None = None) -> str | None:
    """Resolve the result-cache root: explicit argument wins (``off`` etc.
    disables), else ``NEMO_RESULT_CACHE``, else
    ``~/.cache/nemo_tpu/results`` beside the corpus/SVG/jit caches."""
    env = arg if arg is not None else os.environ.get("NEMO_RESULT_CACHE")
    if env is not None:
        env = env.strip()
        if env.lower() in ("", "0", "off", "none", "false"):
            return None
        return os.path.expanduser(env)
    return os.path.join(os.path.expanduser("~"), ".cache", "nemo_tpu", "results")


def shared_cache_dir(arg: str | None = None) -> str | None:
    """Resolve the SHARED (fleet) cache root: explicit argument wins
    (``off`` etc. disables), else ``NEMO_RCACHE_SHARED``.  No default — a
    shared tier is an explicit deployment decision (it names a directory
    every replica can reach), never something to invent locally."""
    env = arg if arg is not None else os.environ.get("NEMO_RCACHE_SHARED")
    if env is None:
        return None
    env = env.strip()
    if env.lower() in ("", "0", "off", "none", "false"):
        return None
    return os.path.expanduser(env)


def lease_ttl_s() -> float:
    """Leader-lease heartbeat TTL (``NEMO_LEASE_TTL_S``, default 10 s): a
    lease whose mtime is older than this is a dead leader's — followers
    may steal it and re-elect."""
    from nemo_tpu.utils.env import env_float

    return max(0.05, env_float("NEMO_LEASE_TTL_S", 10.0))


def resolve_result_cache(
    arg: str | None = None, shared_arg: str | None = None
) -> "ResultCache | None":
    """Resolve the result cache from (argument, env): the local root plus,
    when ``NEMO_RCACHE_SHARED``/``shared_arg`` names one, the fleet's
    shared tier.  The shared tier is a BACKING tier of the result cache,
    not an independent cache: an explicit ``off`` on the result cache
    disables everything, shared tier and leases included — "off means
    off" is what every parity harness that pins ``NEMO_RESULT_CACHE=off``
    relies on.  (A replica that wants ONLY the shared tier points
    ``NEMO_RESULT_CACHE`` at the shared directory itself.)"""
    root = result_cache_dir(arg)
    if root is None:
        return None
    return ResultCache(root, shared_root=shared_cache_dir(shared_arg))


def _max_cache_bytes() -> int:
    """Size cap (bytes): ``NEMO_RESULT_CACHE_MAX_GB`` (default 8; 0/junk
    disables).  Report trees mirror whole debugging.json documents, so the
    cap matters for the same reason the corpus store's does."""
    env = os.environ.get("NEMO_RESULT_CACHE_MAX_GB", "").strip()
    try:
        gb = float(env) if env else 8.0
    except ValueError:
        gb = 0.0
    return int(gb * 1e9) if gb > 0 else 0


def _sha256_file(path: str) -> str:
    sha = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            sha.update(chunk)
    return sha.hexdigest()


def _link_or_copy(src: str, dst: str) -> None:
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


class ResultCache:
    """One result-cache root (plus, for a fleet, the shared tier).  All
    writes are atomic (tmp dir + rename behind a per-kind fcntl publish
    lock) and best-effort: a cache failure must never sink the pipeline."""

    def __init__(self, root: str, shared_root: str | None = None) -> None:
        self.root = root
        #: Where cross-replica leader leases live: the shared tier (None =
        #: no fleet — cross-replica single-flight needs a root every
        #: replica can reach).
        self.lease_root = shared_root
        #: The secondary read/replicate root; None when there is no shared
        #: tier OR the shared root IS the primary (local cache off).
        if shared_root is not None and os.path.abspath(shared_root) == os.path.abspath(root):
            shared_root = None
        self.shared_root = shared_root

    # ------------------------------------------------------------ plumbing

    def _entry_dir(self, kind: str, key: str) -> str:
        return os.path.join(self.root, kind, key)

    @contextmanager
    def _publish_lock(self, root: str, kind: str):
        """Cross-process publish guard for one (root, kind): makes the
        exists-check + rename atomic across replicas racing to publish the
        same content address (the shared tier's concurrent-writer
        contract; also guards two local processes sharing one root).  Lock
        files live under ``<root>/.locks/`` so kind dirs hold only entries
        (+ tmp wreckage) — every existing listdir walk stays valid."""
        ldir = os.path.join(root, ".locks")
        os.makedirs(ldir, exist_ok=True)
        fd = os.open(os.path.join(ldir, f"{kind}.lock"), os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _load_entry_at(self, root: str, kind: str, key: str):
        """One root's verified read: ("hit", entry, dir) | ("miss",) |
        ("stale",) — no counters (the orchestrating :meth:`_load_entry`
        owns them, so a local miss backed by a shared hit is not a miss)."""
        d = os.path.join(root, kind, key)
        path = os.path.join(d, "entry.json")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                entry = json.load(fh)
        except FileNotFoundError:
            return ("miss", None, None)
        except (OSError, ValueError) as ex:
            _log.warning(
                "rcache.entry_unreadable", kind=kind, key=key, root=root,
                error=f"{type(ex).__name__}: {ex}",
            )
            return ("stale", None, None)
        if _verify_on_load():
            for rec in entry.get("manifest", ()):
                p = os.path.join(d, rec["path"])
                try:
                    ok = (
                        os.path.getsize(p) == int(rec["size"])
                        and _sha256_file(p) == rec["sha256"]
                    )
                except OSError:
                    ok = False
                if not ok:
                    _log.error(
                        "rcache.entry_corrupt", kind=kind, key=key, root=root,
                        file=rec["path"],
                        detail="failing the verify pass; recomputing instead "
                        "of serving stale bytes",
                    )
                    return ("stale", None, None)
        return ("hit", entry, d)

    def _load_entry(self, kind: str, key: str):
        """(entry dict, entry dir) on a verified read — local root first,
        then the shared tier — else None.  Misses and stale entries
        counted per kind (a shared-tier hit counts
        ``rcache.<kind>_shared_hit`` in addition to the caller's hit).
        The HIT counter is the caller's to record (:meth:`_hit`) once the
        payload actually decodes — a manifest-valid entry whose payload is
        undecodable must count as stale only, never as both a hit and a
        stale."""
        any_stale = False
        status, entry, d = self._load_entry_at(self.root, kind, key)
        if status == "stale":
            any_stale = True
            obs.metrics.inc(f"rcache.{kind}_stale")
        if status == "hit":
            return entry, d
        if self.shared_root is not None:
            status, entry, d = self._load_entry_at(self.shared_root, kind, key)
            if status == "stale":
                any_stale = True
                obs.metrics.inc(f"rcache.{kind}_stale")
            if status == "hit":
                obs.metrics.inc(f"rcache.{kind}_shared_hit")
                return entry, d
        if not any_stale:
            # A stale entry is invalidation, not a cold miss (the store's
            # counting precedent); only a clean double-miss counts here.
            obs.metrics.inc(f"rcache.{kind}_miss")
        return None

    def _hit(self, kind: str, entry_dir: str) -> None:
        """Record a served hit: counter + LRU last-use stamp."""
        obs.metrics.inc(f"rcache.{kind}_hit")
        try:
            os.utime(os.path.join(entry_dir, "entry.json"))
        except OSError:
            pass

    def _commit_tmp(self, root: str, kind: str, key: str, tmp: str) -> str:
        """Publish a fully built tmp entry dir at ``root``: the
        exists-check + rename runs under the per-kind fcntl lock, so two
        processes racing to publish the same content address commit
        exactly one entry (the loser is counted ``rcache.publish_race``
        and its tmp removed — same key == same bytes, so keeping the
        winner, LRU stamp included, is always correct).  Returns the final
        entry dir."""
        final = os.path.join(root, kind, key)
        with self._publish_lock(root, kind):
            if os.path.isdir(final):
                obs.metrics.inc("rcache.publish_race")
                shutil.rmtree(tmp, ignore_errors=True)
            else:
                try:
                    os.rename(tmp, final)
                except OSError:
                    # A racer on a lockless filesystem beat the rename
                    # anyway; the entry that exists is byte-identical.
                    obs.metrics.inc("rcache.publish_race")
                    shutil.rmtree(tmp, ignore_errors=True)
        return final

    def _replicate_shared(self, src: str, kind: str, key: str) -> None:
        """Copy a just-published entry into the shared tier (fleet
        replication).  Best-effort: a shared-tier outage must not fail the
        local publish; losing the cross-replica race is counted, never an
        error (content-addressed ⇒ the winner's bytes are ours)."""
        root = self.shared_root
        if root is None:
            return
        try:
            final = os.path.join(root, kind, key)
            if os.path.isdir(final):
                obs.metrics.inc("rcache.publish_race")
                return
            tmp = f"{final}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
            os.makedirs(os.path.join(root, kind), exist_ok=True)
            shutil.copytree(src, tmp)
            self._commit_tmp(root, kind, key, tmp)
            obs.metrics.inc(f"rcache.{kind}_shared_put")
            self._evict_over_cap(keep=final, root=root)
        except Exception as ex:
            obs.metrics.inc("rcache.write_failed")
            _log.warning(
                "rcache.shared_replicate_failed", kind=kind, key=key,
                root=root, error=f"{type(ex).__name__}: {ex}",
            )

    def _put_entry(self, kind: str, key: str, build) -> bool:
        """Atomically publish one entry: ``build(tmp_dir) -> entry dict``
        populates the payload and returns the entry body (the manifest is
        appended here).  Publishes to the local root, then replicates to
        the shared tier when one is configured.  Returns False (logged) on
        any failure."""
        try:
            os.makedirs(os.path.join(self.root, kind), exist_ok=True)
            final = self._entry_dir(kind, key)
            tmp = f"{final}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
            os.makedirs(tmp, exist_ok=True)
            try:
                entry = build(tmp)
                manifest = []
                for dirpath, _, files in os.walk(tmp):
                    for f in sorted(files):
                        p = os.path.join(dirpath, f)
                        rel = os.path.relpath(p, tmp)
                        manifest.append(
                            {
                                "path": rel,
                                "size": os.path.getsize(p),
                                "sha256": _sha256_file(p),
                            }
                        )
                entry["manifest"] = manifest
                entry["created"] = time.time()
                with open(os.path.join(tmp, "entry.json"), "w", encoding="utf-8") as fh:
                    json.dump(entry, fh, indent=1)
                self._commit_tmp(self.root, kind, key, tmp)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            obs.metrics.inc(f"rcache.{kind}_put")
            self._replicate_shared(final, kind, key)
            self._evict_over_cap(keep=final)
            return True
        except Exception as ex:
            obs.metrics.inc("rcache.write_failed")
            _log.warning(
                "rcache.write_failed", kind=kind, key=key,
                error=f"{type(ex).__name__}: {ex}",
            )
            return False

    # ------------------------------------------------------------- reports

    def load_report(self, key: str, results_root: str, report_dir: str) -> bool:
        """Restore a cached full report tree into ``report_dir`` (replacing
        any existing report, like Reporter.prepare).  True on a verified
        hit — the caller then writes fresh telemetry and is DONE: no
        backend, no kernel dispatches."""
        got = self._load_entry("report", key)
        if got is None:
            return False
        entry, d = got
        t0 = time.perf_counter()
        with obs.span("report:cache_restore", key=key[:12]):
            os.makedirs(results_root, exist_ok=True)
            tmp = f"{report_dir}.tmp-{uuid.uuid4().hex[:8]}"
            try:
                tree = os.path.join(d, "tree")
                for dirpath, _, files in os.walk(tree):
                    for f in files:
                        src = os.path.join(dirpath, f)
                        rel = os.path.relpath(src, tree)
                        _link_or_copy(src, os.path.join(tmp, rel))
                if os.path.isdir(report_dir):
                    shutil.rmtree(report_dir)
                os.rename(tmp, report_dir)
            except OSError as ex:
                shutil.rmtree(tmp, ignore_errors=True)
                obs.metrics.inc("rcache.restore_failed")
                _log.warning(
                    "rcache.restore_failed", key=key, error=str(ex),
                )
                return False
        self._hit("report", d)
        obs.metrics.observe("rcache.restore_s", time.perf_counter() - t0)
        _log.info(
            "rcache.report_hit", key=key[:12], report_dir=report_dir,
            files=len(entry.get("manifest", ())),
            seconds=round(time.perf_counter() - t0, 3),
        )
        return True

    def put_report(self, key: str, report_dir: str, exclude: frozenset) -> bool:
        """Cache a freshly written report tree (minus ``exclude`` basenames
        — the nondeterministic telemetry set)."""

        def build(tmp: str) -> dict:
            tree = os.path.join(tmp, "tree")
            for dirpath, _, files in os.walk(report_dir):
                for f in files:
                    if f in exclude:
                        continue
                    src = os.path.join(dirpath, f)
                    rel = os.path.relpath(src, report_dir)
                    _link_or_copy(src, os.path.join(tree, rel))
            return {"kind": "report", "key": key}

        return self._put_entry("report", key, build)

    # ------------------------------------------------------------ partials

    def load_partial(self, key: str):
        """A verified cached SegmentPartial (figure files NOT yet restored
        — restore_figures does that into the report tree), or None."""
        from nemo_tpu.analysis.delta import SegmentPartial

        got = self._load_entry("partial", key)
        if got is None:
            return None
        entry, d = got
        try:
            p = SegmentPartial.from_json(entry["partial"])
        except (KeyError, TypeError, ValueError) as ex:
            obs.metrics.inc("rcache.partial_stale")
            _log.warning(
                "rcache.partial_undecodable", key=key,
                error=f"{type(ex).__name__}: {ex}",
            )
            return None
        p.cache_dir = d  # type: ignore[attr-defined]
        self._hit("partial", d)
        return p

    def put_partial(self, key: str, partial, figures_dir: str) -> bool:
        """Cache one segment's partial + its figure files (hardlinked from
        the just-written report's figures/)."""

        def build(tmp: str) -> dict:
            fdir = os.path.join(tmp, "figures")
            for name in partial.fig_files:
                src = os.path.join(figures_dir, name)
                _link_or_copy(src, os.path.join(fdir, name))
            return {"kind": "partial", "key": key, "partial": partial.to_json()}

        return self._put_entry("partial", key, build)

    def restore_figures(self, partial, figures_dir: str) -> int:
        """Place a cached partial's figure files into the report's
        figures/ directory; returns the file count.  Best-effort like
        every cache read: the entry's manifest was verified at load time,
        but a concurrent evictor can rmtree it between load and restore
        (or NEMO_STORE_VERIFY=off skipped the check) — a vanished file is
        counted and logged as an ERROR (the report tree is missing that
        figure), never raised: a cache failure must not sink an analysis
        whose kernel work is already done."""
        d = getattr(partial, "cache_dir", None)
        if d is None:
            return 0
        os.makedirs(figures_dir, exist_ok=True)
        n = 0
        for name in partial.fig_files:
            src = os.path.join(d, "figures", name)
            dst = os.path.join(figures_dir, name)
            try:
                if os.path.exists(dst):
                    os.remove(dst)
                _link_or_copy(src, dst)
            except OSError as ex:
                obs.metrics.inc("rcache.figures_missing")
                _log.error(
                    "rcache.figure_restore_failed", entry=d, file=name,
                    error=f"{type(ex).__name__}: {ex}",
                    detail="cached figure vanished (concurrent eviction or "
                    "unverified entry); the report is missing this figure",
                )
                continue
            n += 1
        obs.metrics.inc("rcache.figures_restored", n)
        return n

    # --------------------------------------------------------------- blobs

    def load_blob(self, namespace: str, key: str) -> bytes | None:
        got = self._load_entry(f"blob_{namespace}", key)
        if got is None:
            return None
        _, d = got
        try:
            with open(os.path.join(d, "payload.bin"), "rb") as fh:
                payload = fh.read()
        except OSError:
            obs.metrics.inc(f"rcache.blob_{namespace}_stale")
            return None
        self._hit(f"blob_{namespace}", d)
        return payload

    def put_blob(self, namespace: str, key: str, payload: bytes) -> bool:
        def build(tmp: str) -> dict:
            with open(os.path.join(tmp, "payload.bin"), "wb") as fh:
                fh.write(payload)
            return {"kind": f"blob_{namespace}", "key": key}

        return self._put_entry(f"blob_{namespace}", key, build)

    def blob_present(self, namespace: str, key: str) -> bool:
        """Cheap existence probe (no verify, no counters) across both
        roots — the fleet follower's poll while its leader runs.  Entries
        appear atomically (tmp + rename), so a present dir is a complete
        entry; the follower's single :meth:`load_blob` on appearance does
        the verified, counted read."""
        for root in (self.root, self.shared_root):
            if root and os.path.isdir(os.path.join(root, f"blob_{namespace}", key)):
                return True
        return False

    # ------------------------------------------------------------ eviction

    _WRECKAGE_MAX_AGE_S = 3600.0

    def _evict_over_cap(self, keep: str, root: str | None = None) -> None:
        """LRU size-cap eviction mirroring the corpus store's: sweep aged
        crash leftovers, then evict least-recently-used entries
        (entry.json mtime, stamped on every hit — the stamp works the same
        on the shared tier, so fleet-wide hits keep an entry warm) until
        under NEMO_RESULT_CACHE_MAX_GB — never the entry just written.
        The ``lease`` kind is never swept: lease files are liveness state,
        not cached content (an evicted lease would look like a dead
        leader)."""
        from nemo_tpu.store import store_size_bytes

        root = self.root if root is None else root
        now = time.time()
        try:
            for kind in os.listdir(root):
                kdir = os.path.join(root, kind)
                if kind == "lease" or kind.startswith(".") or not os.path.isdir(kdir):
                    continue
                for name in os.listdir(kdir):
                    if ".tmp-" not in name:
                        continue
                    path = os.path.join(kdir, name)
                    try:
                        if now - os.path.getmtime(path) < self._WRECKAGE_MAX_AGE_S:
                            continue
                        shutil.rmtree(path, ignore_errors=True)
                        obs.metrics.inc("rcache.gc_wreckage")
                    except OSError:
                        continue
        except OSError:
            pass
        cap = _max_cache_bytes()
        if not cap:
            return
        try:
            entries = []
            for kind in os.listdir(root):
                kdir = os.path.join(root, kind)
                if kind == "lease" or kind.startswith(".") or not os.path.isdir(kdir):
                    continue
                for name in os.listdir(kdir):
                    if ".tmp-" in name:
                        continue
                    path = os.path.join(kdir, name)
                    if not os.path.isdir(path):
                        continue
                    size = store_size_bytes(path)
                    try:
                        used = os.path.getmtime(os.path.join(path, "entry.json"))
                    except OSError:
                        used = 0.0
                    entries.append((used, size, path))
            total = sum(s for _, s, _ in entries)
            if total <= cap:
                return
            for used, size, path in sorted(entries):
                if total <= cap:
                    break
                if os.path.abspath(path) == os.path.abspath(keep):
                    continue
                shutil.rmtree(path, ignore_errors=True)
                total -= size
                obs.metrics.inc("rcache.evicted")
                _log.info(
                    "rcache.evicted", entry=path, freed_mb=round(size / 1e6, 1),
                )
        except OSError as ex:
            _log.warning("rcache.evict_failed", root=root, error=str(ex))


# ---------------------------------------------------------------- leases


class Lease:
    """A cross-replica leader lease: one file under the SHARED cache root
    (``<root>/lease/<namespace>/<key>.lease``), acquired with an
    ``O_CREAT|O_EXCL`` create, kept alive by mtime heartbeats, and
    STEALABLE once the holder's heartbeat is older than the TTL
    (``NEMO_LEASE_TTL_S``) — how a dead leader's followers re-elect.

    The steal runs under a per-namespace fcntl lock with a re-stat, so two
    stealers cannot unlink each other's fresh lease; a heartbeat that
    lands between staleness check and unlink is the accepted race (the
    old leader finds its lease gone at release time, which is harmless:
    the payload it publishes is content-addressed and byte-identical to
    the new leader's).
    """

    def __init__(
        self,
        root: str,
        namespace: str,
        key: str,
        owner: str | None = None,
        ttl_s: float | None = None,
    ) -> None:
        self.dir = os.path.join(root, "lease", namespace)
        self.path = os.path.join(self.dir, f"{key}.lease")
        self.owner = owner or f"{_socket.gethostname()}-{os.getpid()}"
        self.ttl_s = lease_ttl_s() if ttl_s is None else float(ttl_s)
        self._held = False
        #: True after an infrastructure failure (unwritable shared tier)
        #: — distinct from "another replica leads", so the caller can
        #: execute locally NOW instead of waiting out a follower deadline
        #: for a publish that can never arrive.
        self.broken = False

    @property
    def held(self) -> bool:
        return self._held

    def _create(self) -> bool:
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump({"owner": self.owner, "acquired": time.time()}, fh)
        self._held = True
        return True

    def try_acquire(self) -> bool:
        """True when this process now holds the lease (fresh acquire, or a
        steal from a stale holder)."""
        try:
            os.makedirs(self.dir, exist_ok=True)
            if self._create():
                obs.metrics.inc("rcache.lease_acquired")
                return True
            if not self.holder_stale():
                return False
            # Steal: serialize stealers and re-check staleness under the
            # lock so a racing stealer's FRESH lease is never unlinked.
            lock_fd = os.open(
                os.path.join(self.dir, ".lease.lock"), os.O_CREAT | os.O_RDWR, 0o644
            )
            try:
                fcntl.flock(lock_fd, fcntl.LOCK_EX)
                if not self.holder_stale():
                    return False
                try:
                    os.unlink(self.path)
                except FileNotFoundError:
                    pass
                if self._create():
                    obs.metrics.inc("rcache.lease_steal")
                    _log.warning(
                        "rcache.lease_stolen", path=self.path, owner=self.owner,
                        detail="previous leader's heartbeat expired; re-elected",
                    )
                    # A steal means a leader died mid-flight — exactly the
                    # moment a postmortem bundle is worth its disk.
                    obs.flight.trigger(
                        "lease_steal", path=self.path, new_owner=self.owner
                    )
                    return True
                return False
            finally:
                fcntl.flock(lock_fd, fcntl.LOCK_UN)
                os.close(lock_fd)
        except OSError as ex:
            # A shared-tier outage must not wedge the caller — and must be
            # DISTINGUISHABLE from "another replica leads": flag it so the
            # caller executes locally immediately instead of parking on a
            # follower deadline for a publish that can never arrive.
            self.broken = True
            _log.warning("rcache.lease_error", path=self.path, error=str(ex))
            return False

    def holder_stale(self) -> bool:
        """True when the current holder's heartbeat (file mtime) is older
        than the TTL — or the lease vanished between checks."""
        try:
            return time.time() - os.path.getmtime(self.path) > self.ttl_s
        except OSError:
            return True

    def read_owner(self) -> str | None:
        """The lease file's CURRENT owner id, whoever holds it (a follower
        reads this to span-link its trace to the leader's flight), or None
        when the lease is gone/unreadable."""
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                return json.load(fh).get("owner")
        except (OSError, ValueError):
            return None

    def heartbeat(self) -> None:
        """Refresh the holder's liveness stamp (no-op unless held)."""
        if not self._held:
            return
        try:
            os.utime(self.path)
        except OSError:
            pass

    def release(self) -> None:
        """Drop a held lease (idempotent).  Owner-checked: a lease
        already STOLEN by a re-electing follower belongs to the new
        leader now — unlinking it here would orphan that leader mid-run
        and invite a third duplicate execution.  The read-then-unlink
        window is accepted (content-addressed payloads make any residual
        duplicate a counted inefficiency, never a conflict)."""
        if not self._held:
            return
        self._held = False
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                if json.load(fh).get("owner") != self.owner:
                    return  # stolen while we ran; it is the new leader's
        except (OSError, ValueError):
            return
        try:
            os.unlink(self.path)
        except OSError:
            pass
