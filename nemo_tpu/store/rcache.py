"""Content-addressed analysis result cache (sibling of the corpus store).

Three entry kinds under one size-capped root (default
``~/.cache/nemo_tpu/results``; ``NEMO_RESULT_CACHE`` / ``--result-cache``
override, ``off`` disables):

  * ``report/<key>/``  — a full report tree (minus the nondeterministic
    telemetry files): a warm repeat request restores it with ZERO kernel
    dispatches and no backend at all;
  * ``partial/<key>/`` — one store segment's :class:`SegmentPartial` JSON
    plus its rendered figure files: a GROWN corpus maps only its new
    segments and merges these (analysis/delta.py);
  * ``blob/<ns>/<key>`` — small opaque payloads (the sidecar's AnalyzeDir
    response cache).

Keys are produced by analysis/delta.py from (store segment fingerprints,
analysis config, kernel/report ABI versions) — pure content addressing, so
the cache needs no invalidation protocol: any input change produces a new
key and the stale entry ages out via the same LRU size-cap machinery the
corpus store uses (``NEMO_RESULT_CACHE_MAX_GB``, last-use stamped on every
hit).  Every entry carries a sha256 manifest; a corrupted entry fails the
verify pass (``NEMO_STORE_VERIFY=off`` skips it, like the store) and is
treated as a loud, counted miss — never served.

Files are hardlinked between the cache and report trees where the
filesystem allows (the report is regenerated output, and a mutated
hardlinked report file is exactly what the manifest verify catches), with
a copy fallback across devices.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid

from nemo_tpu import obs
from nemo_tpu.obs import log as obs_log
from nemo_tpu.store.npack import _verify_on_load

_log = obs_log.get_logger("nemo.rcache")


def result_cache_dir(arg: str | None = None) -> str | None:
    """Resolve the result-cache root: explicit argument wins (``off`` etc.
    disables), else ``NEMO_RESULT_CACHE``, else
    ``~/.cache/nemo_tpu/results`` beside the corpus/SVG/jit caches."""
    env = arg if arg is not None else os.environ.get("NEMO_RESULT_CACHE")
    if env is not None:
        env = env.strip()
        if env.lower() in ("", "0", "off", "none", "false"):
            return None
        return os.path.expanduser(env)
    return os.path.join(os.path.expanduser("~"), ".cache", "nemo_tpu", "results")


def resolve_result_cache(arg: str | None = None) -> "ResultCache | None":
    root = result_cache_dir(arg)
    return ResultCache(root) if root else None


def _max_cache_bytes() -> int:
    """Size cap (bytes): ``NEMO_RESULT_CACHE_MAX_GB`` (default 8; 0/junk
    disables).  Report trees mirror whole debugging.json documents, so the
    cap matters for the same reason the corpus store's does."""
    env = os.environ.get("NEMO_RESULT_CACHE_MAX_GB", "").strip()
    try:
        gb = float(env) if env else 8.0
    except ValueError:
        gb = 0.0
    return int(gb * 1e9) if gb > 0 else 0


def _sha256_file(path: str) -> str:
    sha = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            sha.update(chunk)
    return sha.hexdigest()


def _link_or_copy(src: str, dst: str) -> None:
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


class ResultCache:
    """One result-cache root.  All writes are atomic (tmp dir + rename)
    and best-effort: a cache failure must never sink the pipeline."""

    def __init__(self, root: str) -> None:
        self.root = root

    # ------------------------------------------------------------ plumbing

    def _entry_dir(self, kind: str, key: str) -> str:
        return os.path.join(self.root, kind, key)

    def _load_entry(self, kind: str, key: str):
        """(entry dict, entry dir) on a verified read, else None — misses
        and stale entries counted and logged per kind.  The HIT counter is
        the caller's to record (:meth:`_hit`) once the payload actually
        decodes — a manifest-valid entry whose payload is undecodable must
        count as stale only, never as both a hit and a stale."""
        d = self._entry_dir(kind, key)
        path = os.path.join(d, "entry.json")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                entry = json.load(fh)
        except FileNotFoundError:
            obs.metrics.inc(f"rcache.{kind}_miss")
            return None
        except (OSError, ValueError) as ex:
            obs.metrics.inc(f"rcache.{kind}_stale")
            _log.warning(
                "rcache.entry_unreadable", kind=kind, key=key,
                error=f"{type(ex).__name__}: {ex}",
            )
            return None
        if _verify_on_load():
            for rec in entry.get("manifest", ()):
                p = os.path.join(d, rec["path"])
                try:
                    ok = (
                        os.path.getsize(p) == int(rec["size"])
                        and _sha256_file(p) == rec["sha256"]
                    )
                except OSError:
                    ok = False
                if not ok:
                    obs.metrics.inc(f"rcache.{kind}_stale")
                    _log.error(
                        "rcache.entry_corrupt", kind=kind, key=key,
                        file=rec["path"],
                        detail="failing the verify pass; recomputing instead "
                        "of serving stale bytes",
                    )
                    return None
        return entry, d

    def _hit(self, kind: str, entry_dir: str) -> None:
        """Record a served hit: counter + LRU last-use stamp."""
        obs.metrics.inc(f"rcache.{kind}_hit")
        try:
            os.utime(os.path.join(entry_dir, "entry.json"))
        except OSError:
            pass

    def _put_entry(self, kind: str, key: str, build) -> bool:
        """Atomically publish one entry: ``build(tmp_dir) -> entry dict``
        populates the payload and returns the entry body (the manifest is
        appended here).  Returns False (logged) on any failure."""
        try:
            os.makedirs(os.path.join(self.root, kind), exist_ok=True)
            final = self._entry_dir(kind, key)
            tmp = f"{final}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
            os.makedirs(tmp, exist_ok=True)
            try:
                entry = build(tmp)
                manifest = []
                for dirpath, _, files in os.walk(tmp):
                    for f in sorted(files):
                        p = os.path.join(dirpath, f)
                        rel = os.path.relpath(p, tmp)
                        manifest.append(
                            {
                                "path": rel,
                                "size": os.path.getsize(p),
                                "sha256": _sha256_file(p),
                            }
                        )
                entry["manifest"] = manifest
                entry["created"] = time.time()
                with open(os.path.join(tmp, "entry.json"), "w", encoding="utf-8") as fh:
                    json.dump(entry, fh, indent=1)
                if os.path.isdir(final):
                    # Same key == same content: keep the existing entry (its
                    # LRU stamp included) rather than replace-racing it.
                    shutil.rmtree(tmp, ignore_errors=True)
                else:
                    try:
                        os.rename(tmp, final)
                    except OSError:
                        shutil.rmtree(tmp, ignore_errors=True)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            obs.metrics.inc(f"rcache.{kind}_put")
            self._evict_over_cap(keep=final)
            return True
        except Exception as ex:
            obs.metrics.inc("rcache.write_failed")
            _log.warning(
                "rcache.write_failed", kind=kind, key=key,
                error=f"{type(ex).__name__}: {ex}",
            )
            return False

    # ------------------------------------------------------------- reports

    def load_report(self, key: str, results_root: str, report_dir: str) -> bool:
        """Restore a cached full report tree into ``report_dir`` (replacing
        any existing report, like Reporter.prepare).  True on a verified
        hit — the caller then writes fresh telemetry and is DONE: no
        backend, no kernel dispatches."""
        got = self._load_entry("report", key)
        if got is None:
            return False
        entry, d = got
        t0 = time.perf_counter()
        with obs.span("report:cache_restore", key=key[:12]):
            os.makedirs(results_root, exist_ok=True)
            tmp = f"{report_dir}.tmp-{uuid.uuid4().hex[:8]}"
            try:
                tree = os.path.join(d, "tree")
                for dirpath, _, files in os.walk(tree):
                    for f in files:
                        src = os.path.join(dirpath, f)
                        rel = os.path.relpath(src, tree)
                        _link_or_copy(src, os.path.join(tmp, rel))
                if os.path.isdir(report_dir):
                    shutil.rmtree(report_dir)
                os.rename(tmp, report_dir)
            except OSError as ex:
                shutil.rmtree(tmp, ignore_errors=True)
                obs.metrics.inc("rcache.restore_failed")
                _log.warning(
                    "rcache.restore_failed", key=key, error=str(ex),
                )
                return False
        self._hit("report", d)
        obs.metrics.observe("rcache.restore_s", time.perf_counter() - t0)
        _log.info(
            "rcache.report_hit", key=key[:12], report_dir=report_dir,
            files=len(entry.get("manifest", ())),
            seconds=round(time.perf_counter() - t0, 3),
        )
        return True

    def put_report(self, key: str, report_dir: str, exclude: frozenset) -> bool:
        """Cache a freshly written report tree (minus ``exclude`` basenames
        — the nondeterministic telemetry set)."""

        def build(tmp: str) -> dict:
            tree = os.path.join(tmp, "tree")
            for dirpath, _, files in os.walk(report_dir):
                for f in files:
                    if f in exclude:
                        continue
                    src = os.path.join(dirpath, f)
                    rel = os.path.relpath(src, report_dir)
                    _link_or_copy(src, os.path.join(tree, rel))
            return {"kind": "report", "key": key}

        return self._put_entry("report", key, build)

    # ------------------------------------------------------------ partials

    def load_partial(self, key: str):
        """A verified cached SegmentPartial (figure files NOT yet restored
        — restore_figures does that into the report tree), or None."""
        from nemo_tpu.analysis.delta import SegmentPartial

        got = self._load_entry("partial", key)
        if got is None:
            return None
        entry, d = got
        try:
            p = SegmentPartial.from_json(entry["partial"])
        except (KeyError, TypeError, ValueError) as ex:
            obs.metrics.inc("rcache.partial_stale")
            _log.warning(
                "rcache.partial_undecodable", key=key,
                error=f"{type(ex).__name__}: {ex}",
            )
            return None
        p.cache_dir = d  # type: ignore[attr-defined]
        self._hit("partial", d)
        return p

    def put_partial(self, key: str, partial, figures_dir: str) -> bool:
        """Cache one segment's partial + its figure files (hardlinked from
        the just-written report's figures/)."""

        def build(tmp: str) -> dict:
            fdir = os.path.join(tmp, "figures")
            for name in partial.fig_files:
                src = os.path.join(figures_dir, name)
                _link_or_copy(src, os.path.join(fdir, name))
            return {"kind": "partial", "key": key, "partial": partial.to_json()}

        return self._put_entry("partial", key, build)

    def restore_figures(self, partial, figures_dir: str) -> int:
        """Place a cached partial's figure files into the report's
        figures/ directory; returns the file count.  Best-effort like
        every cache read: the entry's manifest was verified at load time,
        but a concurrent evictor can rmtree it between load and restore
        (or NEMO_STORE_VERIFY=off skipped the check) — a vanished file is
        counted and logged as an ERROR (the report tree is missing that
        figure), never raised: a cache failure must not sink an analysis
        whose kernel work is already done."""
        d = getattr(partial, "cache_dir", None)
        if d is None:
            return 0
        os.makedirs(figures_dir, exist_ok=True)
        n = 0
        for name in partial.fig_files:
            src = os.path.join(d, "figures", name)
            dst = os.path.join(figures_dir, name)
            try:
                if os.path.exists(dst):
                    os.remove(dst)
                _link_or_copy(src, dst)
            except OSError as ex:
                obs.metrics.inc("rcache.figures_missing")
                _log.error(
                    "rcache.figure_restore_failed", entry=d, file=name,
                    error=f"{type(ex).__name__}: {ex}",
                    detail="cached figure vanished (concurrent eviction or "
                    "unverified entry); the report is missing this figure",
                )
                continue
            n += 1
        obs.metrics.inc("rcache.figures_restored", n)
        return n

    # --------------------------------------------------------------- blobs

    def load_blob(self, namespace: str, key: str) -> bytes | None:
        got = self._load_entry(f"blob_{namespace}", key)
        if got is None:
            return None
        _, d = got
        try:
            with open(os.path.join(d, "payload.bin"), "rb") as fh:
                payload = fh.read()
        except OSError:
            obs.metrics.inc(f"rcache.blob_{namespace}_stale")
            return None
        self._hit(f"blob_{namespace}", d)
        return payload

    def put_blob(self, namespace: str, key: str, payload: bytes) -> bool:
        def build(tmp: str) -> dict:
            with open(os.path.join(tmp, "payload.bin"), "wb") as fh:
                fh.write(payload)
            return {"kind": f"blob_{namespace}", "key": key}

        return self._put_entry(f"blob_{namespace}", key, build)

    # ------------------------------------------------------------ eviction

    _WRECKAGE_MAX_AGE_S = 3600.0

    def _evict_over_cap(self, keep: str) -> None:
        """LRU size-cap eviction mirroring the corpus store's: sweep aged
        crash leftovers, then evict least-recently-used entries
        (entry.json mtime, stamped on every hit) until under
        NEMO_RESULT_CACHE_MAX_GB — never the entry just written."""
        from nemo_tpu.store import store_size_bytes

        now = time.time()
        try:
            for kind in os.listdir(self.root):
                kdir = os.path.join(self.root, kind)
                if not os.path.isdir(kdir):
                    continue
                for name in os.listdir(kdir):
                    if ".tmp-" not in name:
                        continue
                    path = os.path.join(kdir, name)
                    try:
                        if now - os.path.getmtime(path) < self._WRECKAGE_MAX_AGE_S:
                            continue
                        shutil.rmtree(path, ignore_errors=True)
                        obs.metrics.inc("rcache.gc_wreckage")
                    except OSError:
                        continue
        except OSError:
            pass
        cap = _max_cache_bytes()
        if not cap:
            return
        try:
            entries = []
            for kind in os.listdir(self.root):
                kdir = os.path.join(self.root, kind)
                if not os.path.isdir(kdir):
                    continue
                for name in os.listdir(kdir):
                    if ".tmp-" in name:
                        continue
                    path = os.path.join(kdir, name)
                    size = store_size_bytes(path)
                    try:
                        used = os.path.getmtime(os.path.join(path, "entry.json"))
                    except OSError:
                        used = 0.0
                    entries.append((used, size, path))
            total = sum(s for _, s, _ in entries)
            if total <= cap:
                return
            for used, size, path in sorted(entries):
                if total <= cap:
                    break
                if os.path.abspath(path) == os.path.abspath(keep):
                    continue
                shutil.rmtree(path, ignore_errors=True)
                total -= size
                obs.metrics.inc("rcache.evicted")
                _log.info(
                    "rcache.evicted", entry=path, freed_mb=round(size / 1e6, 1),
                )
        except OSError as ex:
            _log.warning("rcache.evict_failed", root=self.root, error=str(ex))
