"""nemo_tpu — a TPU-native rebuild of Nemo, the provenance-graph debugger.

Nemo ingests fault-injection output from Molly (per-run antecedent/consequent
provenance graphs plus failure specs), analyzes it, and emits an HTML debugging
report.  The reference implementation (Go + Neo4j, see /root/reference) runs its
analyses as Cypher traversals; here the same analyses run as batched
integer/boolean array kernels under JAX, vmapped over fault-injection runs and
sharded across a TPU mesh.

Layout (mirrors the reference's layer map, SURVEY.md §1):
  ingest/    - Molly output ETL (reference: faultinjectors/)
  graphs/    - packed-array graph representation + vocab interning
  backend/   - GraphBackend interface (reference: main.go:33-44) with a pure
               Python oracle backend and the JAX/TPU backend
  ops/       - JAX kernels: masked BFS, condition marking, chain contraction,
               longest paths, prototype bitsets, differential provenance
  parallel/  - device-mesh sharding of run batches, collectives
  analysis/  - pipeline orchestration, corrections/extensions synthesis
  report/    - DOT model, figure generation, SVG rendering, HTML report
  models/    - protocol case-study models + the flagship batched pipeline
  dedalus/   - mini Dedalus evaluator + fault injector (stands in for Molly)
  utils/     - timing, logging
"""

__version__ = "0.1.0"
