"""The live watch loop: debounced change detection -> incremental
re-analysis -> atomic report republish -> subscriber push.

One :class:`Watcher` owns one sweep directory.  Each cycle:

  1. **Detect** — the resolved ingest adapter's :meth:`poll_token`
     (ingest/adapters.py: dir mtime + index-file stat; never parses) is
     polled every ``poll_s``; a moved token arms the cycle.  Files named
     by the previous cycle's quarantine records are statted too, so an
     operator (or the injector finishing a half-written file) repairing a
     quarantined run re-arms the loop even when the index is untouched.
  2. **Debounce** — the token must hold still for ``debounce_s`` before
     analysis starts, so a mid-flush index write settles; whatever is
     still half-written after that lands in quarantine (PR 9) instead of
     failing the cycle, and is re-ingested on repair via the store's
     GROWN path.
  3. **Analyze** — a standard :func:`~nemo_tpu.analysis.pipeline.run_debug`
     into a staging generation directory.  With the corpus store and the
     result cache enabled (both default-on) the store appends ONLY the
     new runs as a GROWN segment and the partial tier serves every
     already-mapped segment with zero kernel dispatches — per-update work
     is O(new runs), asserted by the watch smoke via
     ``delta.runs_mapped`` / ``kernel_dispatch_count`` deltas.
  4. **Publish** — the live report name under ``results_root`` is a
     SYMLINK flipped atomically (``os.replace`` of a fresh link) onto the
     new generation directory; a reader mid-walk keeps the previous
     generation, which is swept one flip later.
  5. **Push** — every subscriber queue receives one ``report_update``
     event: update ordinal, new/total run counts, the incrementality
     evidence (runs mapped, segments cached, kernel-dispatch delta), and
     the changed report sections as ``{relpath: sha256[:12]}`` digests.

A SIGKILL'd watcher resumes for free: the next watcher (or any post-hoc
run) consults the same content-addressed partials and maps only what the
dead one never finished — the PR-9 crash-safe-resume contract.

Observability: ``watch.updates`` / ``watch.new_runs`` /
``watch.update_latency_s`` / ``watch.cycle_failed`` metrics and one
``watch:cycle`` span per update, surfaced in the report's telemetry
table.
"""

from __future__ import annotations

import hashlib
import os
import queue as _queue
import threading
import time
import uuid
from dataclasses import dataclass, field

from nemo_tpu import obs
from nemo_tpu.obs import log as _obs_log

_log = _obs_log.get_logger("nemo.watch")

#: Cap on per-event changed-section listings: debugging.json plus a few
#: figures is the common case; a first full-corpus update can touch
#: thousands of files, and the event is a notification, not the payload.
_MAX_CHANGED = 256


@dataclass
class WatchConfig:
    """Watch-loop knobs.  Defaults resolve from env (the CLI/server pass
    explicit values through): ``NEMO_WATCH_POLL_S`` (default 0.5),
    ``NEMO_WATCH_DEBOUNCE_S`` (default 0.25), both warn-and-default on
    junk (the serving-knob policy: a long-lived watcher must not crash-loop
    on a typo'd env)."""

    poll_s: float = None  # type: ignore[assignment]
    debounce_s: float = None  # type: ignore[assignment]
    #: Stop after this many published updates; 0 = run until stopped.
    max_updates: int = 0
    figures: str = "all"
    #: Explicit injector name (``--injector``); None = auto-sniff.
    injector: str | None = None
    #: Give up waiting for the FIRST loadable corpus after this long.
    initial_wait_s: float = 300.0
    #: Extra kwargs forwarded to run_debug (corpus_cache/result_cache...).
    run_debug_kwargs: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        from nemo_tpu.utils.env import env_float

        if self.poll_s is None:
            self.poll_s = env_float("NEMO_WATCH_POLL_S", 0.5, minimum=0.01)
        if self.debounce_s is None:
            self.debounce_s = env_float(
                "NEMO_WATCH_DEBOUNCE_S", 0.25, minimum=0.0
            )


class Watcher:
    """Tail one sweep directory; see the module docstring for the loop.

    ``make_backend`` is called once per update cycle (the CLI precedent:
    one GraphBackend instance per analysis; jit/compile caches are
    process-global, so cycles stay warm).  Thread-safe subscriber fan-out:
    any number of queues receive every event dict."""

    def __init__(
        self,
        corpus_dir: str,
        results_root: str,
        make_backend,
        config: WatchConfig | None = None,
        conn: str = "",
    ) -> None:
        self.corpus_dir = os.path.abspath(corpus_dir)
        self.results_root = os.path.abspath(results_root)
        self.make_backend = make_backend
        self.config = config or WatchConfig()
        self.conn = conn
        self.updates = 0
        self.report_dir: str | None = None  # the live (symlink) path
        self._stop = threading.Event()
        self._subs: list[_queue.SimpleQueue] = []
        self._subs_lock = threading.Lock()
        self._digests: dict[str, str] = {}
        self._runs_total = 0
        self._gen_dirs: list[str] = []  # generation ROOTS, oldest first
        self._quarantine_files: list[str] = []

    # ------------------------------------------------------------ subscribe

    def subscribe(self) -> _queue.SimpleQueue:
        q: _queue.SimpleQueue = _queue.SimpleQueue()
        with self._subs_lock:
            self._subs.append(q)
        return q

    def unsubscribe(self, q) -> None:
        with self._subs_lock:
            if q in self._subs:
                self._subs.remove(q)

    def _push(self, event: dict) -> None:
        with self._subs_lock:
            subs = list(self._subs)
        for q in subs:
            q.put(event)

    # ----------------------------------------------------------------- loop

    def stop(self) -> None:
        self._stop.set()

    def _injector(self):
        from nemo_tpu.ingest import adapters

        return adapters.resolve_injector(self.corpus_dir, self.config.injector)

    def _qstats(self) -> tuple:
        """Stats of every file the last cycle quarantined — the repair
        tripwire component of the poll token."""
        qstats = []
        for path in self._quarantine_files:
            try:
                st = os.stat(path)
                qstats.append((path, st.st_size, st.st_mtime_ns))
            except OSError:
                qstats.append((path, -1, -1))
        return tuple(qstats)

    def _token(self, injector) -> tuple:
        """Change signature: the adapter's poll token plus the stats of
        every file the last cycle quarantined (a repair must re-arm the
        loop even though the index is untouched).  The quarantine stats
        are always the LAST component (the post-cycle refresh in `run`
        replaces exactly that slot)."""
        return (*injector.poll_token(self.corpus_dir), self._qstats())

    def run(self) -> int:
        """Run the watch loop until stopped or ``max_updates`` published;
        returns the number of updates.  Raises only on setup-level
        failures (unsniffable directory past ``initial_wait_s``); per-cycle
        analysis failures are counted (``watch.cycle_failed``), logged,
        pushed as ``watch_error`` events, and retried on the next change."""
        from nemo_tpu.ingest import adapters

        cfg = self.config
        # Config errors fail FAST: an unknown --injector/NEMO_INJECTOR name
        # raises here, before the retry loop — only "the sweep directory has
        # no index yet" is worth waiting out below.
        adapters.injector_arg(cfg.injector)
        deadline = time.monotonic() + cfg.initial_wait_s
        injector = None
        while injector is None and not self._stop.is_set():
            try:
                injector = self._injector()
            except ValueError:
                # The sweep directory may not have its index yet (a watcher
                # started BEFORE the model checker's first flush).
                if time.monotonic() > deadline:
                    raise
                self._stop.wait(cfg.poll_s)
        if injector is None:
            return self.updates
        _log.info(
            "watch.start",
            corpus=self.corpus_dir,
            injector=injector.name,
            poll_s=cfg.poll_s,
            debounce_s=cfg.debounce_s,
        )
        last = None  # token of the last ANALYZED state
        while not self._stop.is_set():
            token = self._token(injector)
            if token == last:
                if cfg.max_updates and self.updates >= cfg.max_updates:
                    break
                self._stop.wait(cfg.poll_s)
                continue
            # Debounce: hold still for debounce_s before analyzing.
            while not self._stop.is_set():
                self._stop.wait(cfg.debounce_s)
                settled = self._token(injector)
                if settled == token:
                    break
                token = settled
            if self._stop.is_set():
                break
            try:
                self._cycle(injector, token)
            except Exception as ex:
                obs.metrics.inc("watch.cycle_failed")
                _log.warning(
                    "watch.cycle_failed",
                    corpus=self.corpus_dir,
                    error=f"{type(ex).__name__}: {ex}",
                )
                obs.flight.trigger(
                    "watch_cycle_failed", corpus=self.corpus_dir,
                    error=f"{type(ex).__name__}: {ex}",
                )
                self._push(
                    {
                        "event": "watch_error",
                        "dir": self.corpus_dir,
                        "detail": f"{type(ex).__name__}: {ex}",
                    }
                )
                # Do NOT record the token: the next poll retries this state
                # (typically a mid-write index that settles shortly).
                self._stop.wait(cfg.poll_s)
                continue
            # Record the PRE-cycle adapter token (an index write landing
            # while the analysis ran must trigger another cycle) but the
            # POST-cycle quarantine stats — `_cycle` just redefined the
            # quarantine watch list, and comparing the fresh list against
            # the pre-cycle snapshot would read as a change and spin a
            # spurious duplicate cycle.  (A repair landing inside the
            # analysis window itself is picked up with the sweep's next
            # index append — the store's pre-parse fingerprints guarantee
            # it can never be served stale.)
            last = (*token[:-1], self._qstats())
            if cfg.max_updates and self.updates >= cfg.max_updates:
                break
        _log.info("watch.stop", corpus=self.corpus_dir, updates=self.updates)
        return self.updates

    # ---------------------------------------------------------------- cycle

    def _cycle(self, injector, token) -> None:
        from nemo_tpu.analysis.delta import kernel_dispatch_count
        from nemo_tpu.analysis.pipeline import report_tree_bytes, run_debug

        cfg = self.config
        name = os.path.basename(os.path.normpath(self.corpus_dir))
        gen = os.path.join(
            self.results_root, ".watch", f"{name}-gen-{self.updates:06d}-{uuid.uuid4().hex[:6]}"
        )
        t0 = time.perf_counter()
        before = obs.metrics.snapshot()["counters"]
        with obs.span(
            "watch:cycle", dir=name, update=self.updates, injector=injector.name
        ):
            result = run_debug(
                self.corpus_dir,
                gen,
                self.make_backend(),
                conn=self.conn,
                figures=cfg.figures,
                report_name=name,
                **cfg.run_debug_kwargs,
            )
        after = obs.metrics.snapshot()["counters"]
        latency = time.perf_counter() - t0

        molly = result.molly
        runs_total = len(molly.runs)
        quarantined = list(getattr(molly, "quarantined", None) or ())
        self._quarantine_files = [
            os.path.join(self.corpus_dir, rec["file"])
            for rec in quarantined
            if rec.get("file") and rec["file"] != injector.index_file
        ]
        new_runs = max(0, runs_total - self._runs_total)
        self._runs_total = runs_total

        # Incrementality evidence (the smoke's O(new runs) assertion).
        def delta_of(key: str) -> int:
            return int(after.get(key, 0)) - int(before.get(key, 0))

        runs_mapped = delta_of("delta.runs_mapped")
        segments_cached = delta_of("delta.segments_cached")
        dispatches = kernel_dispatch_count(after) - kernel_dispatch_count(before)

        # Changed-section digests against the previously published tree.
        tree = report_tree_bytes(result.report_dir)
        digests = {
            p: hashlib.sha256(b).hexdigest()[:12] for p, b in tree.items()
        }
        changed = sorted(
            p for p, h in digests.items() if self._digests.get(p) != h
        )
        removed = sorted(p for p in self._digests if p not in digests)
        self._digests = digests

        live = self._publish(result.report_dir, gen, name)
        self.updates += 1
        obs.metrics.inc("watch.updates")
        obs.metrics.inc("watch.new_runs", new_runs)
        obs.metrics.observe("watch.update_latency_s", latency)
        obs.metrics.gauge("watch.runs_total", runs_total)
        event = {
            "event": "report_update",
            "dir": self.corpus_dir,
            "update": self.updates,
            "runs_total": runs_total,
            "new_runs": new_runs,
            "quarantined": len(quarantined),
            "runs_mapped": runs_mapped,
            "segments_cached": segments_cached,
            "kernel_dispatches": dispatches,
            "update_latency_s": round(latency, 4),
            "report_dir": live,
            "changed_total": len(changed),
            "removed": removed[:_MAX_CHANGED],
            "sections": {p: digests[p] for p in changed[:_MAX_CHANGED]},
        }
        _log.info(
            "watch.update",
            corpus=self.corpus_dir,
            update=self.updates,
            runs_total=runs_total,
            new_runs=new_runs,
            runs_mapped=runs_mapped,
            dispatches=dispatches,
            changed=len(changed),
            seconds=round(latency, 3),
        )
        self._push(event)

    def _publish(self, gen_report_dir: str, gen_root: str, name: str) -> str:
        """Atomically point ``results_root/<name>`` at the new generation:
        a fresh symlink ``os.replace``d over the live name (atomic on
        POSIX).  The PREVIOUS generation directory survives one more flip
        for readers mid-walk; older ones are swept.  A pre-existing REAL
        directory under the live name (an earlier one-shot run) is rotated
        aside once, loudly."""
        import shutil

        live = os.path.join(self.results_root, name)
        os.makedirs(self.results_root, exist_ok=True)
        if os.path.lexists(live) and not os.path.islink(live):
            aside = f"{live}.pre-watch-{uuid.uuid4().hex[:6]}"
            os.rename(live, aside)
            _log.warning(
                "watch.rotated_existing_report", report=live, moved_to=aside
            )
        tmp_link = f"{live}.link-{uuid.uuid4().hex[:6]}"
        os.symlink(gen_report_dir, tmp_link)
        os.replace(tmp_link, live)
        self._gen_dirs.append(gen_root)
        while len(self._gen_dirs) > 2:
            shutil.rmtree(self._gen_dirs.pop(0), ignore_errors=True)
        self.report_dir = live
        return live
