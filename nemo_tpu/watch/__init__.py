"""Live sweep watcher (ISSUE 15): tail a fault-injection sweep directory
WHILE the injector runs, incrementally re-analyzing and republishing the
debug report on every batch of new runs.

Composition of existing layers, no new analysis code: the corpus store's
GROWN append (PR 5) absorbs each batch of new runs as a segment, the
result cache's partial tier (PR 6) makes every update cycle O(new runs)
— cached segments re-load with zero kernel dispatches — quarantine
(PR 9) isolates the half-written files a live sweep inevitably produces
(picked up on repair via the store's GROWN re-ingest), and subscribers
receive ``report_update`` events over the serving tier's
``AnalyzeDirStream`` (PR 8).

Public surface: :class:`~nemo_tpu.watch.watcher.Watcher`,
:class:`~nemo_tpu.watch.watcher.WatchConfig`, and the deterministic
live-sweep simulator :func:`~nemo_tpu.watch.replay.replay_corpus`.
"""

from nemo_tpu.watch.watcher import WatchConfig, Watcher  # noqa: F401
from nemo_tpu.watch.replay import replay_corpus, start_replay  # noqa: F401
