"""Replay driver: feed a FINISHED corpus into a live watcher at a
configurable rate — the deterministic live-sweep simulator (ISSUE 15).

A real model checker appends runs over minutes; tests, smokes and benches
need that arrival pattern reproducibly in seconds.  ``replay_corpus``
materializes an existing corpus into a destination directory in
``generations`` monotonic prefixes (via the ingest adapter's
``materialize_prefix`` — Molly's run-file fan-out and trace-JSON's single
document both replay), sleeping ``interval_s`` between generations,
exactly the way ``grow_corpus_dir`` simulates an incremental sweep for
the delta smoke.  Pair with ``Watcher`` (CLI ``--watch --replay SRC``) or
drive it standalone.
"""

from __future__ import annotations

import math
import os
import threading
import time

from nemo_tpu.obs import log as _obs_log

_log = _obs_log.get_logger("nemo.watch")


def replay_plan(n_runs: int, generations: int) -> list[int]:
    """Monotonic prefix sizes for ``generations`` even cuts of ``n_runs``
    (last cut always the full corpus).  Fewer runs than generations
    degrades to one-run steps."""
    generations = max(1, min(generations, n_runs))
    return [
        max(1, math.ceil(n_runs * (g + 1) / generations))
        for g in range(generations)
    ]


def replay_corpus(
    src_dir: str,
    dst_dir: str,
    generations: int = 3,
    interval_s: float = 1.0,
    injector: str | None = None,
    stop: threading.Event | None = None,
) -> int:
    """Replay ``src_dir`` into ``dst_dir`` in ``generations`` steps;
    returns the number of generations written.  The FIRST generation is
    written immediately (a watcher pointed at ``dst_dir`` starts from it);
    each later one lands after ``interval_s``.  ``stop`` aborts between
    generations."""
    from nemo_tpu.ingest import adapters

    inj = adapters.resolve_injector(src_dir, injector)
    total = inj.count_runs(src_dir)
    plan = replay_plan(total, generations)
    os.makedirs(dst_dir, exist_ok=True)
    _log.info(
        "watch.replay_start",
        src=src_dir,
        dst=dst_dir,
        runs=total,
        generations=len(plan),
        interval_s=interval_s,
    )
    written = 0
    for g, n in enumerate(plan):
        if stop is not None and stop.is_set():
            break
        if g:
            if stop is not None:
                if stop.wait(interval_s):
                    break
            else:
                time.sleep(interval_s)
        inj.materialize_prefix(src_dir, dst_dir, n)
        written += 1
        _log.info(
            "watch.replay_generation", dst=dst_dir, generation=g + 1, runs=n
        )
    return written


def start_replay(
    src_dir: str,
    dst_dir: str,
    generations: int = 3,
    interval_s: float = 1.0,
    injector: str | None = None,
) -> tuple[threading.Thread, threading.Event]:
    """``replay_corpus`` on a daemon thread; returns (thread, stop event)."""
    stop = threading.Event()
    th = threading.Thread(
        target=replay_corpus,
        args=(src_dir, dst_dir, generations, interval_s, injector, stop),
        daemon=True,
        name="nemo-watch-replay",
    )
    th.start()
    return th, stop
