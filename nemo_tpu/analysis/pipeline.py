"""End-to-end debugging pipeline orchestration.

Mirrors the reference's main() fixed stage order (main.go:106-292):
ingest -> init backend -> load raw provenance -> simplify -> hazard analysis
-> prototypes -> pull provenance DOTs -> differential provenance ->
corrections (only when failures exist) -> extensions -> recommendation
assembly -> report (debugging.json + 7 figure families).
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass

from nemo_tpu import obs
from nemo_tpu.backend.base import GraphBackend, NoSuccessfulRunError

_log = obs.log.get_logger("nemo.pipeline")
from nemo_tpu.ingest.molly import MollyOutput
from nemo_tpu.report.writer import Reporter
from nemo_tpu.utils.timing import PhaseTimer

# Top-level recommendation texts (reference: main.go:195,205,212,216).
REC_FAULT = "A fault occurred. Let's try making the protocol correct first."
REC_EXTEND = (
    "Good job, no specification violation. At least one run did not establish "
    "the antecedent, though. Maybe double-check the fault tolerance of the "
    "following rules:"
)
REC_CANT_HELP = (
    "Nemo can't help with this type of bug. Please use the graphs below "
    "regarding differential provenance for guidance to root cause."
)
REC_WELL_DONE = "Well done! No faults, no missing fault tolerance."


@dataclass
class DebugResult:
    molly: MollyOutput
    report_dir: str
    timings: dict[str, float]
    #: RenderScheduler.stats() snapshot for the figure pipeline that produced
    #: this report (dedup ratio, cache hits, workers...); None when the
    #: caller owns the scheduler and drains it after several corpora
    #: (run_debug_dirs fills it in post-drain) or when a legacy sequential
    #: Reporter was passed in.
    figure_stats: dict | None = None


#: Report files that are per-run wall-clock telemetry — inherently
#: nondeterministic across byte-identical reports.  Every byte-parity
#: harness (validate_smoke, the parity tests) skips exactly this set; add
#: here, not in each walker, if another such artifact ever appears.
NONDETERMINISTIC_REPORT_FILES = frozenset({"telemetry.json"})


def report_tree_bytes(root: str) -> dict[str, bytes]:
    """relpath -> content of every deterministic report file under ``root``
    (``NONDETERMINISTIC_REPORT_FILES`` excluded).  THE byte-parity view of a
    report tree — validate_smoke and the bench delta tier both compare
    exactly this, so the exclusion set and the walk can never drift apart."""
    out: dict[str, bytes] = {}
    for dirpath, _, files in os.walk(root):
        for f in files:
            if f in NONDETERMINISTIC_REPORT_FILES:
                continue
            p = os.path.join(dirpath, f)
            with open(p, "rb") as fh:
                out[os.path.relpath(p, root)] = fh.read()
    return out


def _write_telemetry(report_dir: str, timings: dict, figure_stats: dict | None) -> None:
    """Write the report's "Run telemetry" data (telemetry.json next to
    debugging.json): the phase walls, the figure pipeline's dedup/cache
    stats, the process metrics snapshot, and — when the jax backend ran in
    this process — the per-signature kernel cost table (FLOPs / bytes /
    compile walls) and the memory watermarks.  The frontend renders it
    when present and hides the section otherwise, so pre-obs reports stay
    valid; parity harnesses exclude this file (it is per-run wall-clock
    telemetry, inherently nondeterministic across byte-identical reports).
    Best effort: telemetry must never fail a report."""
    doc = {
        "timings": {k: round(v, 6) for k, v in timings.items()},
        "figure_stats": figure_stats,
        "metrics": obs.metrics.snapshot(),
        "trace_id": obs.trace_id(),
    }
    # Kernel cost + memory sections ride along only when the jax backend is
    # already loaded (sys.modules gate: an oracle-backend run must not drag
    # jax in just to report that no kernels ran).
    jb = sys.modules.get("nemo_tpu.backend.jax_backend")
    if jb is not None:
        try:
            costs = jb.kernel_cost_snapshot()
            if costs:
                doc["kernel_cost"] = costs
            doc["memory"] = jb.sample_memory_watermarks()
        except Exception:  # lint: allow-silent-except — telemetry must never fail a report (docstring)
            pass
    # Scheduler decision table (ISSUE 7): one record per scheduled bucket —
    # lane, reason, stolen, predicted-vs-measured walls — same sys.modules
    # gate as the cost table (an oracle run must not drag the scheduler in).
    sch = sys.modules.get("nemo_tpu.parallel.sched")
    if sch is not None:
        try:
            table = sch.sched_snapshot()
            if table:
                doc["sched"] = table
        except Exception:  # lint: allow-silent-except — telemetry must never fail a report (docstring)
            pass
    # Platform profile (ISSUE 19): which routing constants were live for
    # this run and where each came from (env > measured > seeded), plus
    # the calibration fingerprint/wall — same sys.modules gate (an oracle
    # run with the profile subsystem never imported has nothing to say).
    pp = sys.modules.get("nemo_tpu.platform.profile")
    if pp is not None:
        try:
            doc["platform_profile"] = pp.telemetry_section()
        except Exception:  # lint: allow-silent-except — telemetry must never fail a report (docstring)
            pass
    # Per-tenant SLO table (ISSUE 17) — same gate: only a process that
    # actually served traffic has an admission controller to report on.
    adm = sys.modules.get("nemo_tpu.serve.admission")
    if adm is not None:
        try:
            slo = adm.slo_snapshot()
            if slo:
                doc["slo"] = slo
        except Exception:  # lint: allow-silent-except — telemetry must never fail a report (docstring)
            pass
    try:
        with open(os.path.join(report_dir, "telemetry.json"), "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
    except OSError as ex:
        _log.warning("telemetry.write_failed", report_dir=report_dir, error=str(ex))


def _prov_json_str(prov) -> str:
    """Serialized provenance: RawProv splices its C++-held bytes verbatim;
    ProvData encodes through to_json as before."""
    if hasattr(prov, "json_str"):
        return prov.json_str()
    return json.dumps(prov.to_json())


def _run_json_str(run, good_iter: int | None) -> str:
    """One debugging.json run entry, byte-identical to
    json.dumps({**run.to_json(), "goodRunIteration": good_iter}) on the
    object-ingest path (same key order, same omitempty policy,
    datatypes.py:RunData.to_json), but able to splice RawProv byte strings
    without ever parsing provenance in Python."""
    head = getattr(run, "head_json", None)
    if head is not None:
        # Packed-first ingest: the five metadata pairs were canonically
        # serialized by the C++ engine at parse time
        # (nemo_native.cpp:build_run_head) — splice the fragment verbatim
        # instead of rebuilding the typed objects per run.
        pairs: list[tuple[str, str]] = [("", head.decode())]
    else:
        pairs = [
            ("iteration", json.dumps(run.iteration)),
            ("status", json.dumps(run.status)),
            ("failureSpec", json.dumps(run.failure_spec.to_json() if run.failure_spec else None)),
            ("model", json.dumps(run.model.to_json() if run.model else None)),
            ("messages", json.dumps([m.to_json() for m in run.messages])),
        ]
    if run.pre_prov is not None:
        pairs.append(("preProv", _prov_json_str(run.pre_prov)))
    if run.time_pre_holds:
        pairs.append(("timePreHolds", json.dumps(run.time_pre_holds)))
    if run.post_prov is not None:
        pairs.append(("postProv", _prov_json_str(run.post_prov)))
    if run.time_post_holds:
        pairs.append(("timePostHolds", json.dumps(run.time_post_holds)))
    if run.recommendation:
        pairs.append(("recommendation", json.dumps(run.recommendation)))
    if run.corrections:
        pairs.append(("corrections", json.dumps(run.corrections)))
    if run.missing_events:
        pairs.append(("missingEvents", json.dumps([m.to_json() for m in run.missing_events])))
    if run.inter_proto:
        pairs.append(("interProto", json.dumps(run.inter_proto)))
    if run.inter_proto_missing:
        pairs.append(("interProtoMissing", json.dumps(run.inter_proto_missing)))
    if run.union_proto:
        pairs.append(("unionProto", json.dumps(run.union_proto)))
    if run.union_proto_missing:
        pairs.append(("unionProtoMissing", json.dumps(run.union_proto_missing)))
    pairs.append(("goodRunIteration", json.dumps(good_iter)))
    # A pair with an empty key is a pre-rendered multi-pair fragment (the
    # C++ head); every other pair renders as `"key": value`.
    return "{" + ", ".join(v if not k else f'"{k}": {v}' for k, v in pairs) + "}"


def select_figure_iters(
    policy: str, iters: list[int], failed_iters: list[int], good_iter: int | None
) -> list[int]:
    """Figure materialization policy (VERDICT r1: explicit at stress scale).

      all       every run gets figures — the reference behavior
                (main.go:251-289 renders all 7 families for all runs)
      failed    failed runs + the good baseline run
      sample:N  N evenly-spaced failed runs + N evenly-spaced successes +
                the good run — bounded figure count regardless of corpus
                size (a 10k-run stress corpus can have thousands of
                failures; rendering them all is the 'failed' policy)
      none      debugging.json only, no figures

    debugging.json always covers every run regardless of policy."""
    if policy in ("", "all"):
        return list(iters)
    sel: set[int] = set()
    include_good = False
    if policy == "none":
        pass
    elif policy == "failed":
        sel = set(failed_iters)
        include_good = True
    elif policy.startswith("sample:"):
        n = int(policy.split(":", 1)[1])
        failed_set = set(failed_iters)
        others = [i for i in iters if i not in failed_set]
        for pool in (list(failed_iters), others):
            if pool and n > 0:
                stride = max(1, len(pool) // n)
                sel.update(pool[::stride][:n])
        include_good = n > 0
    else:
        raise ValueError(
            f"unknown figure policy {policy!r} (expected all, failed, sample:N, none)"
        )
    # The good baseline run always renders under the restrictive policies —
    # including on an all-success corpus (ADVICE r2: 'failed'/'sample:N'
    # used to render nothing when no run failed).
    if include_good and good_iter is not None:
        sel.add(good_iter)
    return [i for i in iters if i in sel]


def _choose_packed_ingest(
    backend: GraphBackend, save_corpus_path: str | None, store=None
) -> bool:
    """Auto ingest policy: the packed-first loader (C++ ETL, RawProv
    placeholders) applies when the backend consumes packed arrays directly
    and nothing downstream needs the Python provenance object tree
    (--save-corpus packs from ProvData, so it pins the object loader).
    An enabled corpus store also qualifies on lib-less hosts: a warm
    ``.npack`` load is packed arrays with no C++ involvement, and a cold
    one parses via the object loader and POPULATES, so the next run is
    warm (nemo_tpu/store)."""
    if not getattr(backend, "supports_packed_ingest", False) or save_corpus_path:
        return False
    from nemo_tpu.ingest.native import native_available

    return native_available() or store is not None


def _resolve_ingest_mode(
    backend, ingest: str, save_corpus_path=None, store=None
) -> bool:
    """ingest mode -> use_packed, with validation (single definition shared
    by run_debug and run_debug_dirs so the policy cannot drift)."""
    if ingest == "auto":
        return _choose_packed_ingest(backend, save_corpus_path, store)
    if ingest == "native":
        if not getattr(backend, "supports_packed_ingest", False):
            raise ValueError(
                "ingest='native' requires a packed-ingest backend (jax/service); "
                f"{type(backend).__name__} consumes provenance objects"
            )
        if save_corpus_path:
            raise ValueError(
                "ingest='native' is incompatible with --save-corpus "
                "(corpus bundling packs from the Python object tree)"
            )
        from nemo_tpu.ingest.native import native_available, native_error

        if not native_available():
            # Fail fast HERE: _ingest's store-miss branch would otherwise
            # silently serve the pure-Python loader, a different ETL than
            # the one explicitly pinned.
            raise RuntimeError(
                f"ingest='native' requested but the native library is "
                f"unavailable: {native_error()}"
            )
        return True
    if ingest == "python":
        return False
    raise ValueError(f"unknown ingest mode {ingest!r} (expected auto, native, python)")


def _ingest(fault_inj_out: str, use_packed: bool, store=None, consult_store=True):
    """One corpus directory -> MollyOutput.  On the packed path the corpus
    store is consulted FIRST: a warm hit mmaps the persisted arrays +
    serialized strings in milliseconds (nemo_tpu/store — growing
    directories are appended to incrementally); a miss/stale/corrupt store
    falls back loudly to the parse path and repopulates, so the next
    invocation hits.  The object path (oracle backends, --save-corpus)
    never touches the store.  ``consult_store=False`` skips straight to
    parse+populate — for callers that already took (and counted) the miss
    themselves (the sidecar's AnalyzeDir after a load_corpus miss).

    Parse dispatch goes through the fault-injector adapter seam
    (ingest/adapters.py, ``--injector``/``NEMO_INJECTOR``): every front
    end — Molly, trace-JSON, future injectors — lands in the same
    MollyOutput and the same store-populate path, so nothing below this
    function is adapter-specific.  The C++ packed-first ETL applies only
    where the resolved adapter is ``native_capable`` (the Molly layout);
    other layouts parse through their adapter and reach packed arrays via
    the store populate, exactly like a lib-less host."""
    from nemo_tpu.ingest import adapters

    if use_packed and store is not None and consult_store:
        molly = store.load_packed(fault_inj_out)
        if molly is not None:
            return molly
    if use_packed:
        from nemo_tpu.ingest.native import load_molly_output_packed, native_available

        injector = adapters.resolve_injector(fault_inj_out)
        # Snapshot BEFORE parsing: a file mutated while the parse runs must
        # mismatch the fingerprint the populate stores, so the NEXT load
        # re-parses instead of serving a HIT over mixed content.
        snap = store.snapshot(fault_inj_out) if store is not None else None
        if native_available() and injector.native_capable:
            try:
                molly = load_molly_output_packed(fault_inj_out)
            except Exception as ex:
                # Quarantine fallback (ISSUE 9): the C++ engine parses the
                # whole directory in one pass and aborts on the first
                # malformed run; the Python object loader isolates per run,
                # so one truncated provenance file degrades that run to the
                # quarantine instead of sinking a 10k-run ingest.
                from nemo_tpu.utils.env import quarantine_enabled

                if not quarantine_enabled():
                    raise
                _log.warning(
                    "ingest.native_failed_quarantine_fallback",
                    corpus=fault_inj_out,
                    error=f"{type(ex).__name__}: {ex}",
                    detail="re-parsing with the per-run-isolating object "
                    "loader (NEMO_QUARANTINE=off restores fail-fast)",
                )
                obs.metrics.inc("ingest.native_fallback")
                molly = injector.load(fault_inj_out)
        else:
            # Lib-less host, non-Molly layout, or a corrupt store that just
            # fell back: the adapter's object loader serves any backend,
            # and the populate below makes the next run a warm mmap load.
            molly = injector.load(fault_inj_out)
        if store is not None:
            header = store.put(fault_inj_out, molly, snapshot=snap)
            if isinstance(header, dict):
                # The populate's segment identities ride on the parsed
                # object too, so the COLD run's analysis results are
                # content-addressed (store/rcache.py) — the very next
                # request can then be a full report-cache hit.
                from nemo_tpu.store import attach_store_provenance

                sd = store.store_dir(fault_inj_out)
                attach_store_provenance(molly, sd, header)
                nc = getattr(molly, "native_corpus", None)
                if nc is not None:
                    attach_store_provenance(nc, sd, header)
        return molly
    return adapters.load_output(fault_inj_out)


def _attach_ingest_dir(ex: BaseException, d: str) -> BaseException:
    """Annotate an ingest exception with the corpus directory it came from
    (in-place, preserving the exception type): the first string arg gets the
    suffix, or — for arg shapes like OSError's (errno, strerror) — the first
    string among the args; exceptions with no string arg gain one."""
    note = f"(while ingesting {d})"
    if isinstance(ex, OSError) and isinstance(getattr(ex, "strerror", None), str):
        # OSError renders from .strerror (captured at construction), not
        # from args — annotate the attribute str() actually shows.
        if note not in ex.strerror:
            ex.strerror = f"{ex.strerror} {note}"
        return ex
    args = list(ex.args)
    for i, a in enumerate(args):
        if isinstance(a, str):
            if note not in a:
                args[i] = f"{a} {note}"
            break
    else:
        args.append(note)
    try:
        ex.args = tuple(args)
    except Exception:  # lint: allow-silent-except — exotic exception types keep their args; attribution best-effort
        pass
    return ex


def corpus_report_names(dirs: list[str]) -> list[str]:
    """Collision-free report directory names for several corpora sharing
    one results_root: the directory basename when unique across the batch,
    else basename-<8-hex sha256 of the realpath> — stable across runs (the
    same corpus path always maps to the same report dir), so bookmarks and
    diff tooling keep working.  Raises when two entries resolve to the
    SAME directory: both analyses would race one report tree, and no
    naming scheme fixes that."""
    import hashlib

    basenames = [os.path.basename(os.path.normpath(d)) for d in dirs]
    dupes = {b for b in basenames if basenames.count(b) > 1}
    names = [
        f"{b}-{hashlib.sha256(os.path.realpath(d).encode()).hexdigest()[:8]}"
        if b in dupes
        else b
        for d, b in zip(dirs, basenames)
    ]
    clashes = {n for n in names if names.count(n) > 1}
    if clashes:
        raise ValueError(
            f"corpus directories resolve to the same report name(s) "
            f"{sorted(clashes)}: the same directory was listed more than "
            "once (identical realpaths cannot be disambiguated); each "
            "-faultInjOut must name a distinct corpus"
        )
    return names


def run_debug_dirs(
    dirs: list[str],
    results_root: str,
    make_backend,
    prefetch: bool = True,
    **kwargs,
) -> "list[DebugResult]":
    """run_debug over several corpus directories with ingest/compute
    OVERLAP (VERDICT r4 task 5): while corpus k analyzes, a worker thread
    parses corpus k+1 — the C++ ETL runs behind a GIL-releasing ctypes
    call, so on a device deployment the parse hides under the device
    dispatch/transfer waits (and under the report phase's native SVG
    calls).  This is the in-process twin of the sidecar's
    analyze_dir_pipelined (service/client.py).

    Figure rendering is ALSO overlapped: one shared RenderScheduler spans
    all directories, so corpus k's unique SVGs render in the worker pool
    while corpus k+1's kernels dispatch; everything drains (and the SVG
    files land) before this returns, with the aggregate stats attached to
    every result's figure_stats and the drain wall in
    figure_stats["drain_wall_s"].

    `make_backend` is called once per directory (a GraphBackend instance
    per corpus, like the sequential loop it replaces).  kwargs flow to
    run_debug.  With prefetch=False this is exactly the sequential loop.

    Reports write to results_root/<name> with collision-free names
    (corpus_report_names): the directory basename when unique, and
    basename-<8-hex realpath hash> when several corpora share one — a
    duplicate basename used to be rejected outright because the later
    report's prepare() would silently delete the earlier one.  The same
    directory listed TWICE is still rejected (identical realpath hashes —
    nothing can disambiguate two analyses racing one report directory).
    save_corpus_path is rejected for the shared-kwargs reason: every
    corpus would overwrite the same .npz bundle (ADVICE r5).

    On an effectively 1-core host the prefetch thread is skipped even with
    prefetch=True (utils.effective_cpu_count): a producer thread cannot
    overlap with the consumer on one core, so the GIL handoffs are pure
    overhead — ingest runs inline, exactly the sequential loop.
    """
    import threading

    from nemo_tpu.utils import effective_cpu_count

    prefetch = prefetch and effective_cpu_count() > 1

    from nemo_tpu.store import resolve_store

    store = resolve_store(kwargs.get("corpus_cache"))

    if kwargs.get("save_corpus_path"):
        raise ValueError(
            "save_corpus_path is not supported by run_debug_dirs: kwargs are "
            "shared across directories, so every corpus would overwrite the "
            "same .npz bundle; call run_debug per directory with distinct "
            "paths instead"
        )
    report_names = corpus_report_names(dirs)
    if not dirs:
        return []
    # Backends are constructed lazily, one per iteration, and dropped after
    # their corpus completes — retaining them all would keep every corpus's
    # parsed runs and cached device results alive at once (O(dirs) memory
    # where the sequential loop is O(1)).  The probe instance only answers
    # the ingest-mode policy.
    use_packed = _resolve_ingest_mode(
        make_backend(), kwargs.get("ingest", "auto"), kwargs.get("save_corpus_path"),
        store,
    )

    results: list[DebugResult] = []
    prefetched: list = [None, None]  # (molly, exception) of the NEXT dir

    def prefetch_next(d: str) -> None:
        try:
            # The span makes the ingest/compute overlap VISIBLE: it lives on
            # the prefetch thread's track, riding under the previous
            # corpus's analysis phases on the main thread.
            with obs.span("ingest:prefetch", dir=os.path.basename(d)):
                prefetched[0] = _ingest(d, use_packed, store)
        except BaseException as ex:  # re-raised on the consuming thread
            # A bare re-raise on the consumer loses WHICH directory failed —
            # with several corpora in flight that made multi-corpus failures
            # unattributable; pin the dir into the message here, where it is
            # known.
            prefetched[1] = _attach_ingest_dir(ex, d)

    from nemo_tpu.report.render import RenderScheduler

    th: "threading.Thread | None" = None
    molly = None
    scheduler = RenderScheduler()
    try:
        for k, d in enumerate(dirs):
            if th is not None:
                th.join()
                if prefetched[1] is not None:
                    raise prefetched[1]
                molly = prefetched[0]
                prefetched[0] = prefetched[1] = None
            th = None
            if prefetch and k + 1 < len(dirs):
                th = threading.Thread(
                    target=prefetch_next, args=(dirs[k + 1],), daemon=True
                )
                th.start()
            results.append(
                run_debug(
                    d,
                    results_root,
                    make_backend(),
                    molly=molly,
                    render_scheduler=scheduler,
                    report_name=report_names[k],
                    **kwargs,
                )
            )
            molly = None
        # Settle the figure pipeline: whatever didn't finish under the
        # analysis overlap renders/writes now, so every SVG exists before
        # this returns — the same contract as the sequential loop.
        import time as _time

        t0 = _time.perf_counter()
        stats = scheduler.drain()
        stats["drain_wall_s"] = round(_time.perf_counter() - t0, 3)
    finally:
        # Best-effort settle even when a later corpus failed mid-loop: the
        # reports already completed must keep their SVGs (the sequential
        # loop's contract); the original exception stays the one raised.
        try:
            scheduler.drain()
        except Exception:  # lint: allow-silent-except — best-effort settle on the failure path; the original exception stays the one raised
            pass
        scheduler.close()
    for r in results:
        r.figure_stats = stats
        # Result-cache publication deferred from run_debug: its SVGs were
        # pending in the shared scheduler until the drain above.
        _flush_result_cache(r)
        # The telemetry written during each run_debug predates the shared
        # scheduler's drain (figure_stats was None then); refresh it with
        # the aggregate figure stats and the now-complete metrics.
        _write_telemetry(r.report_dir, r.timings, stats)
    return results


def run_debug(
    fault_inj_out: str,
    results_root: str,
    backend: GraphBackend,
    conn: str = "",
    reporter: Reporter | None = None,
    save_corpus_path: str | None = None,
    profile_dir: str | None = None,
    figures: str = "all",
    ingest: str = "auto",
    molly=None,
    render_scheduler=None,
    corpus_cache: str | None = None,
    result_cache: str | None = None,
    report_name: str | None = None,
) -> DebugResult:
    """Full debug pipeline.  With profile_dir set, the analysis phases run
    under jax.profiler.trace — open the directory with TensorBoard or
    xprof to see per-kernel device timelines (SURVEY.md §5: the rebuild's
    tracing story).  `figures` is the figure materialization policy
    (select_figure_iters).  `ingest` selects the ETL: "python" (object
    loader), "native" (packed-first C++ loader, array backends only), or
    "auto" (native when the backend supports it and the library builds).

    Figure SVGs render through the dedup/cache/parallel pipeline
    (report/render.py) by default, drained inside the report phase.  With
    `render_scheduler` supplied the figures are submitted to it and NOT
    drained — the caller overlaps rendering with its own later work and
    drains when ready (run_debug_dirs).  An explicitly passed `reporter`
    whose .scheduler is None keeps the sequential per-figure render loop —
    the byte-parity oracle path.  `corpus_cache` overrides the persistent
    corpus store root (NEMO_CORPUS_CACHE; "off" disables) consulted by the
    packed ingest path.

    The analysis itself runs as a per-store-segment MAP plus an associative
    REDUCE (analysis/delta.py): when `result_cache` (NEMO_RESULT_CACHE;
    "off" disables, default ~/.cache/nemo_tpu/results) resolves and the
    corpus was served by the store, the full report tree and the
    per-segment partials are cached content-addressed — a repeat request
    restores the report with ZERO kernel dispatches, and a GROWN corpus
    maps only its new segments and merges the cached partials.  A profiled
    run (`profile_dir`) never consults the result cache: the point of a
    profile is watching the kernels run.  `report_name` overrides the
    report directory name under results_root (default: molly.run_name —
    run_debug_dirs passes collision-free names)."""
    import contextlib

    from nemo_tpu.analysis import delta
    from nemo_tpu.store import resolve_store
    from nemo_tpu.store.rcache import resolve_result_cache

    trace_ctx: contextlib.AbstractContextManager = contextlib.nullcontext()
    if profile_dir:
        import jax

        trace_ctx = jax.profiler.trace(profile_dir)
    timer = PhaseTimer()

    store = resolve_store(corpus_cache)
    # Fail fast with the reason, not deep in the pipeline: RawProv
    # placeholders crash object backends/--save-corpus only after the
    # full native ingest already ran.
    use_packed = _resolve_ingest_mode(backend, ingest, save_corpus_path, store)

    with timer.phase("ingest"):
        # `molly` pre-supplied: the caller ingested out-of-band (the
        # overlapped multi-corpus driver run_debug_dirs parses corpus k+1
        # while corpus k analyzes) — the phase records ~0 and the ingest
        # wall lives on the prefetch thread instead of the critical path.
        if molly is None:
            molly = _ingest(fault_inj_out, use_packed, store)
    if save_corpus_path:
        from nemo_tpu.graphs.corpus import pack_corpus, save_corpus

        with timer.phase("save_corpus"):
            save_corpus(pack_corpus(molly), save_corpus_path)
    iters = molly.get_runs_iters()
    failed_iters = molly.get_failed_runs_iters()

    run_name = report_name or molly.run_name
    this_results_dir = os.path.join(results_root, run_name)
    # The result cache is bypassed for a profiled run (the point of a
    # profile is watching the kernels run) and for an explicitly passed
    # reporter (the sequential byte-parity ORACLE path — serving it from
    # cache would make every oracle comparison vacuous).
    rcache = (
        None
        if (profile_dir or reporter is not None)
        else resolve_result_cache(result_cache)
    )

    # Tier 1 — whole-report cache: every segment fingerprint + the
    # config/ABI blob addresses the full report tree.  A verified hit
    # restores it and returns without even initializing the backend:
    # zero kernel dispatches, no figure rendering, no recommendation
    # assembly (delta-smoke and the bench delta_tier assert exactly this).
    report_key = (
        delta.report_cache_key(molly, figures) if rcache is not None else None
    )
    if report_key is not None:
        with timer.phase("report"):
            hit = rcache.load_report(report_key, results_root, this_results_dir)
        if hit:
            timings = timer.as_dict()
            _write_telemetry(this_results_dir, timings, None)
            return DebugResult(
                molly=molly,
                report_dir=this_results_dir,
                timings=timings,
                figure_stats=None,
            )

    # The baseline good run, chosen at the PIPELINE level (the single
    # definition backends delegate to — analysis/delta.py:choose_good_run):
    # the reference hard-codes run 0 and silently emits nonsense when run 0
    # failed (differential-provenance.go:22); on an all-failed corpus diff
    # + corrections are skipped with a warning instead of raising.
    # Computed unconditionally (ADVICE r2): the restrictive figure policies
    # include the good baseline run even on an all-success corpus.
    good_iter = delta.choose_good_run(molly)
    if good_iter is None and failed_iters:
        _log.warning(
            "pipeline.no_successful_run",
            detail="skipping differential provenance and correction "
            "synthesis (nothing to diff against)",
            corpus=fault_inj_out,
        )
    baseline_iter = delta.choose_baseline_run(molly, good_iter)
    fig_iters = select_figure_iters(figures, iters, failed_iters, good_iter)
    fig_set = set(fig_iters)

    # Tier 2 — per-segment partials: consult the cache per store segment,
    # map only the segments it cannot serve, reduce over cached + fresh.
    legacy = not getattr(backend, "supports_delta", False)
    segments = delta.attach_positions(delta.corpus_segments(molly), molly)
    cached: list[tuple[object, object]] = []  # (Segment, SegmentPartial)
    partial_keys: dict[str, str] = {}
    if rcache is not None and not legacy:
        for seg in segments:
            k = delta.partial_cache_key(
                seg, segments, good_iter, baseline_iter, figures
            )
            if k is None:
                continue
            partial_keys[seg.name] = k
            p = rcache.load_partial(k)
            if p is not None:
                cached.append((seg, p))
    cached_names = {seg.name for seg, _ in cached}
    to_map = [s for s in segments if s.name not in cached_names]
    n_cached_runs = sum(s.n_runs for s, _ in cached)
    obs.metrics.inc("delta.segments_cached", len(cached))
    obs.metrics.inc("delta.segments_mapped", len(to_map))
    obs.metrics.inc("delta.runs_cached", n_cached_runs)
    obs.metrics.inc("delta.runs_mapped", len(molly.runs) - n_cached_runs)
    if cached:
        _log.info(
            "delta.plan",
            corpus=fault_inj_out,
            segments_cached=len(cached),
            segments_mapped=len(to_map),
            runs_cached=n_cached_runs,
            runs_mapped=len(molly.runs) - n_cached_runs,
        )

    mo = delta.MapOutput()
    checkpointed: dict[str, object] = {}  # seg name -> already-published partial
    streamed = False
    stream_reducer = None
    stream_fresh: dict[str, object] = {}  # failed/unattempted checkpoint residue
    if to_map:
        from nemo_tpu.analysis import stream as stream_mod
        from nemo_tpu.utils import chaos
        from nemo_tpu.utils.env import env_flag

        pos_by_iter = {}
        for pos, r in enumerate(molly.runs):
            pos_by_iter.setdefault(r.iteration, pos)
        # Out-of-core streaming (ISSUE 12): a store-served corpus with
        # several segments to map streams them through the mesh one at a
        # time behind the double-buffered prefetch (analysis/stream.py) —
        # peak memory O(segment + reduce state) instead of O(corpus),
        # byte-identical reports (per-run artifacts are batch-independent,
        # the reduce order-insensitive).  NEMO_STREAM=off restores the
        # in-memory sweep.
        streamed = stream_mod.use_streaming(molly, backend, to_map, legacy=legacy)
        # Crash-safe resume (ISSUE 9): when several segments need mapping
        # and their partials will be cached anyway, map them ONE AT A TIME
        # and publish each segment's partial (figures included) to the
        # result cache as soon as it completes — a SIGKILL mid-sweep then
        # loses only the in-flight segment, and the rerun's tier-2 consult
        # serves the finished ones (delta.segments_cached) and maps only
        # the rest, producing a byte-identical report.  NEMO_CHECKPOINT=0
        # restores the single-map sweep (marginally fewer dispatches: the
        # anchor verbs re-run per segment on this path).  Streamed runs
        # ride this same path, so they are crash-resumable for free.
        incremental = (
            len(to_map) > 1
            and bool(partial_keys)
            and rcache is not None
            and env_flag("NEMO_CHECKPOINT", True)
        )
        map_groups = (
            [[s] for s in to_map] if (incremental or streamed) else [to_map]
        )

        def build_view(group):
            own_rows = sorted(r for s in group for r in range(s.start, s.stop))
            own_row_set = set(own_rows)
            own_set = {molly.runs[r].iteration for r in own_rows}
            # Anchor runs ride along as CONTEXT when they live in a
            # cached (or another group's) segment: the differential
            # verbs diff against the good run's graph and extensions
            # read the baseline run's antecedent, so the map's view
            # must contain them even though their per-run artifacts
            # come from elsewhere.
            anchor_rows = {
                pos_by_iter[it]
                for it in (good_iter, baseline_iter)
                if it is not None and pos_by_iter[it] not in own_row_set
            }
            view_rows = sorted(own_row_set | anchor_rows)
            molly_view = (
                molly
                if len(view_rows) == len(molly.runs)
                else delta.subset_molly(molly, view_rows)
            )
            return molly_view, own_set

        if streamed:
            # The anchor verbs run UNGATED per segment (publish semantics)
            # even when nothing will be cached: every partial then carries
            # identical anchor content, which is what makes the tree merge
            # order-insensitive.
            publish = True
            stream_reducer = delta.TreeReducer()
            for _seg, p in cached:
                stream_reducer.push(p)
            group_iter = stream_mod.stream_groups(
                map_groups, build_view, backend, conn, timer=timer
            )
        else:
            publish = bool(partial_keys)

            def _serial_groups():
                for group in map_groups:
                    molly_view, own_set = build_view(group)
                    with timer.phase("init"):
                        backend.init_graph_db(conn, molly_view)
                    yield stream_mod.StagedGroup(
                        group=group,
                        view=molly_view,
                        own_set=own_set,
                        backend=backend,
                        shared_backend=True,
                    )

            group_iter = _serial_groups()

        with trace_ctx:
            for staged in group_iter:
                group = staged.group
                try:
                    group_mo = delta.map_runs(
                        staged.backend,
                        staged.view,
                        fault_inj_out,
                        good_iter,
                        fig_set,
                        staged.own_set,
                        timer,
                        publish=publish,
                    )
                finally:
                    staged.backend.close_db()
                    staged.release()
                if streamed:
                    # Bounded reduce state: the report phase keeps only the
                    # figure dots; the per-run artifacts travel in the
                    # segment partial, pushed into the k-ary tree reducer
                    # and — where cacheable — dropped to the rcache NOW, so
                    # the segment's working set frees before the next one
                    # stages in.
                    mo.merge_figures(group_mo)
                    seg = group[0]
                    partial = group_mo.as_partial(seg, molly)
                    key = partial_keys.get(seg.name)
                    published = False
                    if incremental and key is not None:
                        published = _publish_segment_checkpoint(
                            rcache, key, partial, group_mo
                        )
                        if published:
                            checkpointed[seg.name] = True
                            obs.metrics.inc("delta.partial_checkpoints")
                            _log.info(
                                "delta.checkpoint",
                                corpus=fault_inj_out,
                                segment=seg.name,
                                published=len(checkpointed),
                                remaining=len(to_map) - len(checkpointed),
                            )
                            chaos.on_segment_published(len(checkpointed))
                    if not published and key is not None:
                        stream_fresh[seg.name] = partial
                    stream_reducer.push(partial)
                    stream_mod.note_segment_done()
                    continue
                mo.merge(group_mo)
                if incremental:
                    seg = group[0]
                    key = partial_keys.get(seg.name)
                    if key is not None:
                        partial = group_mo.as_partial(seg, molly)
                        # Marked checkpointed ONLY on a successful publish:
                        # a transiently failing cache write must leave the
                        # segment in `fresh`, so the end-of-run flush gets
                        # a second chance at it (the pre-checkpoint
                        # behavior) instead of dropping it entirely.
                        if _publish_segment_checkpoint(rcache, key, partial, group_mo):
                            checkpointed[seg.name] = partial
                            obs.metrics.inc("delta.partial_checkpoints")
                            _log.info(
                                "delta.checkpoint",
                                corpus=fault_inj_out,
                                segment=seg.name,
                                published=len(checkpointed),
                                remaining=len(to_map) - len(checkpointed),
                            )
                            # Chaos kill point: SIGKILL after N published
                            # checkpoints (the resume scenario's crash).
                            chaos.on_segment_published(len(checkpointed))

    with timer.phase("reduce"):
        if legacy:
            # No per-run decomposition: the map ran the global verbs over
            # the whole corpus; one pass-through partial carries the
            # per-failed-run missing events and the anchor content.
            partials = [
                delta.SegmentPartial(
                    iters=list(iters),
                    missing=mo.missing,
                    corrections=mo.corrections,
                    extensions=mo.extensions,
                )
            ]
            fresh: dict[str, object] = {}
        elif streamed:
            # Streamed reduce (ISSUE 12): every partial — cached and fresh
            # — was already pushed into the k-ary tree reducer as its
            # segment completed; finish from its live frontier (O(arity *
            # log S) partials, byte-equal to the flat list).  Only
            # failed/unattempted checkpoint publishes remain for the
            # end-of-run flush.
            fresh = stream_fresh
            partials = stream_reducer.partials()
        elif not partial_keys and not cached:
            # Nothing cacheable (anonymous corpus or cache off): skip the
            # per-segment JSON slicing and feed the map output straight
            # through as one in-memory partial.
            fresh = {}
            partials = [
                delta.SegmentPartial(
                    iters=list(iters),
                    proto_ordered=mo.proto_ordered,
                    present=mo.present,
                    missing=mo.missing,
                    achieved=mo.achieved,
                    corrections=mo.corrections,
                    extensions=mo.extensions,
                    ext_candidates=mo.ext_candidates,
                    good_proto=mo.good_proto,
                )
            ]
        else:
            # Checkpointed segments were published mid-map (crash-safe
            # resume); keep them out of the end-of-run puts but in the
            # reduce (order-insensitive, so the split cannot matter).
            fresh = {
                s.name: mo.as_partial(s, molly)
                for s in to_map
                if s.name not in checkpointed
            }
            partials = (
                [p for _, p in cached]
                + [checkpointed[s.name] for s in to_map if s.name in checkpointed]
                + list(fresh.values())
            )
        red = delta.reduce_partials(partials, molly, good_iter, legacy=mo.legacy)

    # Recommendation assembly, 4-way priority (main.go:190-217).  The
    # reference indexes its positional runs slice with iteration numbers
    # (main.go:195); resolve by iteration explicitly so non-contiguous or
    # reordered iterations stay correct.
    runs = molly.get_output()
    by_iter = {r.iteration: r for r in runs}
    for i in iters:
        run = by_iter[i]
        if red.corrections:
            run.recommendation = [REC_FAULT, *red.corrections]
        elif failed_iters and good_iter is None:
            # Failures exist but there was no good run to synthesize
            # corrections from; "well done" / "no violation" would be a lie.
            run.recommendation = [REC_CANT_HELP]
        elif red.extensions:
            run.recommendation = [REC_EXTEND, *red.extensions]
        elif not red.all_achieved:
            run.recommendation = [REC_CANT_HELP]
        else:
            run.recommendation = [REC_WELL_DONE]
        run.inter_proto = red.inter
        run.union_proto = red.union

    for f in failed_iters:
        run = by_iter[f]
        run.corrections = red.corrections
        run.missing_events = red.missing.get(f, [])
        run.inter_proto_missing = red.inter_miss.get(f, [])
        run.union_proto_missing = red.union_miss.get(f, [])

    # Reporting (main.go:239-292).
    fig_stats: dict | None = None
    with timer.phase("report"):
        own_scheduler = None
        if reporter is None:
            if render_scheduler is None:
                from nemo_tpu.report.render import RenderScheduler

                render_scheduler = own_scheduler = RenderScheduler()
            reporter = Reporter(scheduler=render_scheduler)
        elif render_scheduler is not None:
            reporter.scheduler = render_scheduler
        reporter.prepare(results_root, this_results_dir)

        # Each run entry carries the backend's chosen good-run iteration so
        # the report frontend points its diff layer stack at the right run
        # instead of re-deriving the policy in JS (ADVICE r2).  Extra key on
        # the reference schema; the reference frontend ignores unknown keys.
        with open(os.path.join(this_results_dir, "debugging.json"), "w", encoding="utf-8") as fh:
            # Assembled by string splicing, NOT one json.dumps over object
            # trees: on the packed-first ingest path each run's pre/post
            # provenance exists only as a C++-serialized byte string
            # (ingest/native.py:RawProv) spliced in verbatim — byte-identical
            # to what the object path would have encoded (tests/test_fast_ingest.py).
            # Streamed, not ", ".join(...): the join would materialize the
            # whole multi-hundred-MB document a second time at stress scale
            # before the single write; identical bytes either way.
            fh.write("[")
            for j, r in enumerate(runs):
                if j:
                    fh.write(", ")
                fh.write(_run_json_str(r, good_iter))
            fh.write("]")

        # Suggested repairs (ISSUE 13): the corpus-ranked correction/
        # extension synthesis document (analysis/synth.py), rendered by the
        # frontend as the "Suggested repairs" section with per-candidate
        # supporting-run counts and example run links.  Deterministic and
        # route-independent (the synth parity suites pin all three routes
        # byte-equal), part of the cached report tree; absent only for
        # backends without synthesis hooks.
        if red.repairs is not None:
            with open(
                os.path.join(this_results_dir, "repairs.json"), "w", encoding="utf-8"
            ) as fh:
                json.dump(red.repairs, fh, indent=1)

        # Degraded-runs sidecar (ISSUE 9): the quarantined set, rendered by
        # the frontend as the "Degraded runs" section.  Deterministic (part
        # of the cached report tree; report_cache_key covers it), absent on
        # healthy corpora.
        quarantined = getattr(molly, "quarantined", None)
        if quarantined:
            with open(
                os.path.join(this_results_dir, "quarantine.json"), "w", encoding="utf-8"
            ) as fh:
                json.dump(
                    sorted(quarantined, key=lambda r: r["position"]), fh, indent=1
                )

        try:
            # Freshly mapped runs render through the scheduler; cached
            # segments' figures restore from the partial entries (rendered
            # by the run that populated them — same renderer version, part
            # of the cache key, so byte-identical).
            _generate_map_figures(reporter, fig_iters, mo)
            for _seg, p in cached:
                rcache.restore_figures(p, reporter.figures_dir)

            if own_scheduler is not None:
                # Internally owned pipeline: settle it here so the report
                # phase keeps its meaning (all figures on disk when the
                # phase closes).
                fig_stats = own_scheduler.drain()
        finally:
            if own_scheduler is not None:
                own_scheduler.close()

    timings = timer.as_dict()
    _write_telemetry(this_results_dir, timings, fig_stats)
    result = DebugResult(
        molly=molly,
        report_dir=this_results_dir,
        timings=timings,
        figure_stats=fig_stats,
    )
    # Cache publication needs the SVGs ON DISK.  When this call drained its
    # own figure pipeline (or rendered inline through a sequential
    # reporter), publish now; when an external scheduler still holds
    # pending renders (run_debug_dirs), defer — the driver flushes after
    # its shared drain.
    if rcache is not None:
        result._rcache_pending = (
            rcache,
            report_key,
            [
                (partial_keys[name], p)
                for name, p in fresh.items()
                if name in partial_keys
            ],
        )
        drained = own_scheduler is not None or (
            render_scheduler is None and getattr(reporter, "scheduler", None) is None
        )
        if drained:
            _flush_result_cache(result)
    return result


def _generate_map_figures(reporter, fig_iters, mo) -> None:
    """Render one MapOutput's figure families through ``reporter`` — THE
    kind-by-kind sequence, shared by the report phase and the segment
    checkpoint publisher so a new figure family can never reach one and
    silently miss the other (the resumed run's restore-vs-render parity
    depends on the two emitting identical file sets)."""
    own_fig = [i for i in fig_iters if i in mo.hazard]

    def dots(d: dict) -> list:
        return [d[i] for i in own_fig]

    reporter.generate_figures(own_fig, "spacetime", dots(mo.hazard))
    reporter.generate_figures(own_fig, "pre_prov", dots(mo.pre))
    reporter.generate_figures(own_fig, "post_prov", dots(mo.post))
    reporter.generate_figures(own_fig, "pre_prov_clean", dots(mo.pre_clean))
    reporter.generate_figures(own_fig, "post_prov_clean", dots(mo.post_clean))
    diff_fig_iters = [f for f in fig_iters if f in mo.diff]
    reporter.generate_figures(
        diff_fig_iters, "diff_post_prov-diff", [mo.diff[f] for f in diff_fig_iters]
    )
    reporter.generate_figures(
        diff_fig_iters,
        "diff_post_prov-failed",
        [mo.diff_failed[f] for f in diff_fig_iters],
    )


def _publish_segment_checkpoint(rcache, key: str, partial, seg_mo) -> bool:
    """Crash-safe resume (ISSUE 9): publish one freshly mapped segment's
    partial to the result cache IMMEDIATELY, figures included, so a killed
    process resumes from it.  The segment's figures render here into a
    throwaway staging dir through the standard render pipeline (dedup +
    persistent SVG content cache), so the report phase's later render of
    the same figures is a cache hit and byte-identical.  Best-effort like
    every cache write — but the caller must know whether it WORKED (False):
    a failed checkpoint leaves the segment for the end-of-run flush rather
    than silently unpublished."""
    import shutil
    import tempfile

    try:
        if not partial.fig_files:
            return bool(rcache.put_partial(key, partial, figures_dir=""))
        from nemo_tpu.report.render import RenderScheduler
        from nemo_tpu.report.writer import Reporter

        stage = tempfile.mkdtemp(prefix="nemo-ckpt-figs-")
        try:
            rs = RenderScheduler()
            rep = Reporter(scheduler=rs)
            rep.figures_dir = stage
            try:
                _generate_map_figures(rep, seg_mo.own_iters, seg_mo)
                rs.drain()
            finally:
                rs.close()
            return bool(rcache.put_partial(key, partial, stage))
        finally:
            shutil.rmtree(stage, ignore_errors=True)
    except Exception as ex:
        obs.metrics.inc("rcache.checkpoint_failed")
        _log.warning(
            "delta.checkpoint_failed", key=key[:12],
            error=f"{type(ex).__name__}: {ex}",
        )
        return False


def _flush_result_cache(result: DebugResult) -> None:
    """Publish a completed run's result-cache entries (report tree +
    fresh segment partials).  Requires every figure file to be on disk —
    callers that deferred rendering to a shared scheduler call this after
    the drain.  Best-effort like every cache write."""
    pending = result.__dict__.pop("_rcache_pending", None)
    if not pending:
        return
    rcache, report_key, partial_puts = pending
    figures_dir = os.path.join(result.report_dir, "figures")
    for key, partial in partial_puts:
        rcache.put_partial(key, partial, figures_dir)
    if report_key is not None:
        rcache.put_report(
            report_key, result.report_dir, NONDETERMINISTIC_REPORT_FILES
        )
