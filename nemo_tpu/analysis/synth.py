"""Corpus-ranked correction/extension synthesis (ISSUE 13 tentpole).

The reference's end product is GenerateCorrections / GenerateExtensions —
the CIDR paper's debugging recommendations — but it only ever computes them
from ONE run (the good run's triggers, the baseline run's async boundary).
This module is the corpus-scale generalization: candidates are extracted
PER RUN by batched kernels (the map side), then scored and ranked ACROSS
the whole corpus by an order-insensitive support-count reduce (this
module) — a correction explaining 900 of 1000 failed runs outranks one
explaining 3, which is the "what should I fix first" signal the per-run
reference never had.

Candidate families:

  * **corrections**: the anti-join between the good run's prototype rule
    tables and each failed run's clean consequent graph — a table the
    healthy execution's causal chain contains but the failed run never
    produced is a candidate repair site.  Both sides are existing batched
    kernel outputs (``proto_bits`` for the good row, ``proto_present`` per
    failed row — CSR frontier waves on every route), so the anti-join adds
    no graph sweeps; the reduce counts supporting failed runs per table.
  * **extensions**: async rules adjacent to the antecedent's condition
    boundary (extensions.go:63-67), extracted for EVERY run by the new
    batched ``synth_ext`` kernel (ops/sparse_device.py device twin,
    ops/sparse_host.py bincount-scatter twin, the per-run PGraph walk of
    analysis/queries.py demoted to the parity oracle) instead of only the
    baseline run; the reduce counts supporting runs per table.

Associativity contract: every per-run candidate set is keyed by iteration
and independent of which other runs shared its batch (the synth parity
suites pin this), the good-run table set is ANCHOR content identical on
every publishing partial, and :func:`build_repairs` imposes global run
order itself — so merging segment partials is permutation-safe, ranked
repairs delta-update when a corpus grows, and the streamed tree reduce
produces byte-identical rankings (tests/test_synth.py).

Cache-key coverage: per-run candidates travel in ``SegmentPartial``
(keyed on segment fingerprint + the good/baseline ANCHOR identities —
analysis/delta.py:partial_cache_key — so a changed good-run anchor
invalidates every ranked repair) and the ranked document rides the report
tree (report_cache_key); ``ANALYSIS_ABI_VERSION`` was bumped with these
keys so cached pre-synthesis reports recompute loudly.
"""

from __future__ import annotations

#: Supporting-run links shown per ranked candidate (repairs.json
#: ``example_runs``): the smallest supporting iterations, ascending — a
#: deterministic, permutation-safe sample regardless of corpus size.
MAX_EXAMPLE_RUNS = 5


def synth_impl_env() -> str:
    """Parse + validate NEMO_SYNTH_IMPL — the route knob of the synthesis
    kernel family, following the NEMO_ANALYSIS_IMPL precedent (loud on
    junk: a typo silently resolving to auto would change which engine
    extracts candidates in exactly the dimension the operator pinned):

      auto           resolved by the process that owns the device
                     (JaxBackend._resolve_synth_impl / the ServiceBackend
                     override)
      python         the per-run PGraph oracle (analysis/queries.py walks,
                     one graph at a time) — the pre-batching reference
                     path, kept as the parity oracle
      sparse         the batched bincount-scatter host twin
                     (ops/sparse_host.py:synth_ext_host)
      sparse_device  the batched gather/scatter device kernel via the
                     ``synth_ext`` executor verb (ops/sparse_device.py)
    """
    from nemo_tpu.utils.env import env_choice

    return env_choice(
        "NEMO_SYNTH_IMPL", "auto", ("auto", "python", "sparse", "sparse_device")
    )


def synth_host_work_budget() -> int:
    """Per-bucket crossover for the synthesis route under auto on a DEVICE
    backend: buckets at or below this B x (V + E) work run the host
    bincount twin instead of paying a device dispatch (the
    NEMO_ANALYSIS_HOST_WORK economics one verb over — the synth kernel is
    a handful of single-step scatters, so the dispatch's fixed RTT
    dominates even deeper into the work axis).  NEMO_SYNTH_HOST_WORK
    overrides; a measured platform profile supplies its fitted crossover
    when the env is unset (ISSUE 19 — env > profile > seeded)."""
    from nemo_tpu.utils.env import env_int

    try:
        from nemo_tpu.platform import profile as _pp

        measured = _pp.profile_value("synth_host_work")
    except Exception:  # lint: allow-silent-except — a broken profile store must degrade to the seeded crossover, not sink routing (docstring)
        measured = None
    return env_int("NEMO_SYNTH_HOST_WORK", 100000 if measured is None else int(measured))


def correction_suggestion(table: str) -> str:
    """Presentation-ready repair line for one correction candidate (the
    report frontend renders it next to the support count)."""
    return f"<code>{table}(node, ...)</code>"


def extension_suggestion(table: str) -> str:
    """Presentation-ready hardening line for one extension candidate —
    the same clause shape as analysis/corrections.py:synthesize_extensions
    so the ranked list and the reference-format recommendation agree."""
    return f"<code>{table}(node, ...)@async :- ...;</code>"


def _rank(support: "dict[str, list[int]]", total: int, suggest) -> list[dict]:
    """Support dict (table -> supporting iterations) -> ranked candidate
    records, most-supported first, table name as the deterministic
    tiebreak.  Example runs are the smallest supporting iterations —
    independent of insertion (segment) order."""
    out = [
        {
            "table": t,
            "support": len(its),
            "total": total,
            "example_runs": sorted(its)[:MAX_EXAMPLE_RUNS],
            "suggestion": suggest(t),
        }
        for t, its in support.items()
    ]
    out.sort(key=lambda c: (-c["support"], c["table"]))
    return out


def correction_candidates(good_proto, present) -> list[str]:
    """The anti-join for ONE failed run: good-run prototype tables absent
    from the run's clean consequent graph, sorted.  ``present`` is the
    run's distinct clean rule tables (the fused kernels' proto_present
    row, already in every SegmentPartial)."""
    return sorted(set(good_proto or ()) - set(present or ()))


def build_repairs(
    good_proto,
    ext_by_run: "dict[int, list[str]]",
    present: "dict[int, list[str] | set[str]]",
    molly,
    good_iter: "int | None",
) -> dict:
    """The order-insensitive support-count reduce: merge per-run candidate
    sets into the corpus-ranked repair document (repairs.json).

    Pure function of (anchor table set, per-run candidate dicts, the
    corpus run order) — per-run dicts are iteration-keyed and disjoint
    across segments, so any merge order of partials feeds identical inputs
    here, and the ranking (support desc, table asc) plus the ascending
    example-run sample are order-free.  This is what makes ranked repairs
    rcache-cacheable per segment, streamable through the tree reduce, and
    delta-updatable when a grown corpus's new segment shifts the
    corpus-wide ranking."""
    failed_iters = molly.get_failed_runs_iters()
    run_iters = molly.get_runs_iters()

    corr_support: dict[str, list[int]] = {}
    if good_iter is not None and good_proto:
        for f in failed_iters:
            for t in correction_candidates(good_proto, present.get(f)):
                corr_support.setdefault(t, []).append(f)

    ext_support: dict[str, list[int]] = {}
    for r in run_iters:
        for t in ext_by_run.get(r, ()):
            ext_support.setdefault(t, []).append(r)

    return {
        "good_run": good_iter,
        "runs_total": len(run_iters),
        "failed_total": len(failed_iters),
        "corrections": _rank(corr_support, len(failed_iters), correction_suggestion),
        "extensions": _rank(ext_support, len(run_iters), extension_suggestion),
    }
