"""Per-segment map + associative reduce over the analysis pipeline.

The corpus store (nemo_tpu/store) persists a Molly directory as append-only
*segments*: an incremental sweep appends a new segment and never rewrites an
old one.  This module decomposes ``run_debug``'s analysis along exactly that
axis:

  * **map** (:func:`map_runs`): the per-run verbs — condition marking,
    simplification, hazard/provenance figures, per-run prototype rule
    tables, good-anchored differential provenance — executed over one set
    of runs (a segment, or the whole corpus when nothing is cached).  Every
    per-run output is independent of which other runs share the batch
    (the sparse/dense parity suites pin this), so mapping a subset produces
    bit-identical per-run artifacts.
  * **reduce** (:func:`reduce_partials`): the cross-run aggregation —
    prototype intersection/union (analysis/protos.py set algebra over
    per-run tables), per-failed-run missing lists, the achieved-antecedent
    count, correction/extension tables, recommendation assembly inputs.
    The reduce consumes per-run artifacts keyed by iteration and imposes
    global run order itself, so merging partials is order-insensitive —
    the property that lets a grown corpus merge cached per-segment
    partials with freshly mapped ones (and, later, lets the run axis shard
    across workers).

A :class:`SegmentPartial` is the serializable intermediate between the two
phases: everything the reduce needs from one segment's runs, as plain
strings/ints (vocabulary-id free, so vocab growth across appends cannot
invalidate it), plus the names of the segment's rendered figure files.
Partials and whole report trees are cached content-addressed by
``nemo_tpu/store/rcache.py``.

Cache-key anchors: the differential verbs are computed *against* the
corpus's good run, and extensions against its baseline run, so a partial is
keyed on (its own segment fingerprint, the good/baseline identity and the
fingerprints of the segments holding them, the analysis config, and the
ABI/versions below).  :data:`ANALYSIS_ABI_VERSION` must be bumped whenever
a kernel or reduce semantic changes output bytes.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

import numpy as np

from nemo_tpu import obs
from nemo_tpu.ingest.datatypes import MissingEvent

#: Bump when any analysis kernel / verb / reduce semantic changes its
#: OUTPUT (not its speed): every cached result is keyed on this, so a bump
#: invalidates the whole result cache at once — the cheap, always-correct
#: fleet-wide invalidation (the corpus store's NPACK_ABI_VERSION precedent).
#: v2: corpus-ranked correction/extension synthesis (ISSUE 13) — partials
#: carry per-run extension candidates + the good-run prototype anchor, and
#: report trees gain repairs.json; pre-synthesis cache entries must
#: recompute loudly, never serve a report missing its ranked repair list.
ANALYSIS_ABI_VERSION = 2

_log = obs.log.get_logger("nemo.delta")

#: Figure families rendered per selected run (writer.py naming:
#: run_<iter>_<family>.{dot,svg}), in report generation order.
FIG_FAMILIES = (
    "spacetime",
    "pre_prov",
    "post_prov",
    "pre_prov_clean",
    "post_prov_clean",
)
#: Families rendered only for figure-selected FAILED runs when a good run
#: exists to diff against.
DIFF_FAMILIES = ("diff_post_prov-diff", "diff_post_prov-failed")


# ---------------------------------------------------------------------------
# good/baseline run selection (pure functions of the corpus)
# ---------------------------------------------------------------------------


def choose_good_run(molly) -> int | None:
    """The baseline successful run used for differential provenance — the
    first status-success run that ACHIEVED the consequent, else the first
    status-success run, else None.  Single definition shared with
    ``GraphBackend.good_run_iter`` (backend/base.py) so the pipeline-level
    choice and every backend's internal choice can never drift."""
    succ = molly.get_success_runs_iters()
    if not succ:
        return None
    by_iter = {r.iteration: r for r in molly.runs}
    for i in succ:
        if by_iter[i].time_post_holds:
            return i
    return succ[0]


def choose_baseline_run(molly, good_iter: int | None) -> int | None:
    """The good run when one exists, else the first run (the run whose
    antecedent provenance seeds extension candidates —
    ``GraphBackend.baseline_run_iter``)."""
    if good_iter is not None:
        return good_iter
    return molly.runs[0].iteration if molly.runs else None


# ---------------------------------------------------------------------------
# segments
# ---------------------------------------------------------------------------


@dataclass
class Segment:
    """One store segment's slice of the corpus (or the whole corpus as a
    single anonymous segment when no store served the ingest)."""

    name: str
    fingerprint: str | None  # None = anonymous (not cacheable)
    start: int  # first global run POSITION
    n_runs: int

    @property
    def stop(self) -> int:
        return self.start + self.n_runs


def corpus_segments(molly) -> list[Segment]:
    """Segment spans of this corpus, from the store metadata the ingest
    attached (``molly.store_segments``); a single anonymous segment
    otherwise.  Positions index ``molly.runs`` — the store consolidates
    segments in append order, so segment rows are contiguous."""
    meta = getattr(molly, "store_segments", None)
    if not meta or sum(int(m["n_runs"]) for m in meta) != len(molly.runs):
        return [Segment("all", None, 0, len(molly.runs))]
    out, start = [], 0
    for m in meta:
        n = int(m["n_runs"])
        out.append(Segment(str(m["name"]), m.get("fingerprint"), start, n))
        start += n
    return out


def config_blob(figures: str) -> dict:
    """Everything besides the corpus content that determines report bytes:
    the figure policy and every output-affecting version.  Part of every
    cache key."""
    from nemo_tpu.report.native import REPORT_ABI_VERSION
    from nemo_tpu.report.svg import RENDER_FORMAT_VERSION
    from nemo_tpu.store.npack import NPACK_ABI_VERSION

    return {
        "figures": figures or "all",
        "analysis_abi": ANALYSIS_ABI_VERSION,
        "report_abi": REPORT_ABI_VERSION,
        "render_format": RENDER_FORMAT_VERSION,
        "npack_abi": NPACK_ABI_VERSION,
    }


def _key(doc: dict) -> str:
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


def report_cache_key(molly, figures: str) -> str | None:
    """Content address of the full report tree: every segment fingerprint +
    the config/ABI blob + the quarantine set (the report's "Degraded runs"
    section — quarantine.json — is part of the tree, and two corpora with
    identical healthy segments but different quarantined runs must not
    share an entry).  None when any segment is anonymous (no store —
    nothing fingerprints the content, so a hit is impossible)."""
    segs = corpus_segments(molly)
    if any(s.fingerprint is None for s in segs):
        return None
    return _key(
        {"kind": "report", "config": config_blob(figures),
         "segments": [s.fingerprint for s in segs],
         "quarantined": [
             [q["position"], q.get("file"), q.get("error")]
             for q in getattr(molly, "quarantined", None) or ()
         ]}
    )


def partial_cache_key(
    seg: Segment,
    segments: list[Segment],
    good_iter: int | None,
    baseline_iter: int | None,
    figures: str,
) -> str | None:
    """Content address of one segment's partial.  Besides the segment's own
    fingerprint and the config blob, the key pins the ANCHOR context: the
    good/baseline run identities and the fingerprints of the segments
    holding them — differential provenance, corrections and extensions are
    functions of those runs' graphs, so a changed anchor (e.g. a grown
    corpus whose first achieving success appears in a NEW segment) must
    miss every old partial.  Delta caching is disabled (None) for the
    ``sample:N`` figure policy: its selection depends on the whole corpus's
    run list, so per-segment figure ownership does not decompose."""
    if seg.fingerprint is None:
        return None
    if (figures or "all").startswith("sample:"):
        return None

    def anchor_fp(it: int | None) -> str | None:
        if it is None:
            return None
        pos = _position_of(segments, it)
        if pos is None:
            return None
        return next(
            (s.fingerprint for s in segments if s.start <= pos < s.stop), None
        )

    # Anchor positions resolve through the segment table built from the
    # SAME molly, so a lookup failure means an anonymous corpus — handled
    # by the fingerprint None check above.
    return _key(
        {
            "kind": "partial",
            "config": config_blob(figures),
            "segment": seg.fingerprint,
            "good": [good_iter, anchor_fp(good_iter)],
            "baseline": [baseline_iter, anchor_fp(baseline_iter)],
        }
    )


#: iteration -> global position memo per segment-table identity (tiny).
def _position_of(segments: list[Segment], iteration: int) -> int | None:
    tbl = getattr(segments[0], "_pos_by_iter", None)
    return None if tbl is None else tbl.get(iteration)


def attach_positions(segments: list[Segment], molly) -> list[Segment]:
    """Give the segment table an iteration->position index (duplicate
    iterations keep the FIRST position, like every by-iter dict in the
    pipeline)."""
    tbl: dict[int, int] = {}
    for pos, r in enumerate(molly.runs):
        tbl.setdefault(r.iteration, pos)
    for s in segments:
        s._pos_by_iter = tbl  # type: ignore[attr-defined]
    return segments


# ---------------------------------------------------------------------------
# sub-corpus views (the delta path maps only new rows)
# ---------------------------------------------------------------------------

_COND_FIELDS = (
    "table_id",
    "label_id",
    "time_id",
    "type_id",
    "is_goal",
    "node_mask",
    "edge_src",
    "edge_dst",
    "edge_mask",
    "n_nodes",
    "n_goals",
    "chain_linear",
)


class _CorpusRowView:
    """Row-subset view of a NativeCorpus/StoreCorpus: batch arrays sliced to
    the selected rows (fancy-index copies — delta maps are small by
    construction), per-run string accessors delegated to the base corpus by
    original row."""

    def __init__(self, base, rows: list[int]) -> None:
        from nemo_tpu.ingest.native import NativeCondBatch

        idx = np.asarray(rows, dtype=np.int64)
        self._base = base
        self._rows = list(rows)
        self.n_runs = len(rows)
        self.v = base.v
        self.e = base.e
        self.max_depth = base.max_depth
        self.tables = base.tables
        self.labels = base.labels
        self.times = base.times
        self.pre_tid = base.pre_tid
        self.post_tid = base.post_tid
        self.iteration = np.asarray(base.iteration)[idx]
        self.success = np.asarray(base.success)[idx]

        def gather(cb, f):
            # A lazily consolidating multi-segment store batch exposes
            # take(): gather the view's rows straight from the per-segment
            # mmaps — same values as consolidated[idx] without ever
            # materializing the corpus-wide plane (the streamed path's
            # bounded working set, store/reader.py:LazyCondBatch).
            take = getattr(cb, "take", None)
            if take is not None:
                return take(f, idx)
            return np.asarray(getattr(cb, f))[idx]

        self.pre = NativeCondBatch(
            **{f: gather(base.pre, f) for f in _COND_FIELDS}
        )
        self.post = NativeCondBatch(
            **{f: gather(base.post, f) for f in _COND_FIELDS}
        )

    def cond(self, name: str):
        return self.pre if name == "pre" else self.post

    def prov_json(self, cond_name: str, row: int) -> bytes:
        return self._base.prov_json(cond_name, self._rows[row])

    def run_head_json(self, row: int) -> bytes:
        return self._base.run_head_json(self._rows[row])

    def lazy_node_ids(self, cond_name: str, row: int) -> list[str]:
        return self._base.lazy_node_ids(cond_name, self._rows[row])


def subset_molly(molly, rows: list[int]):
    """MollyOutput view over a row subset (global positions, ascending).
    Run objects are SHARED with the full molly — the map phase never
    mutates them; only the reduce-side recommendation assembly does, and it
    operates on the full molly."""
    from nemo_tpu.ingest.molly import MollyOutput

    out = MollyOutput(
        run_name=molly.run_name,
        output_dir=molly.output_dir,
        ships_spacetime_dots=getattr(molly, "ships_spacetime_dots", True),
    )
    out.runs = [molly.runs[r] for r in rows]
    for run in out.runs:
        out.runs_iters.append(run.iteration)
        if run.succeeded:
            out.success_runs_iters.append(run.iteration)
        else:
            out.failed_runs_iters.append(run.iteration)
    nc = getattr(molly, "native_corpus", None)
    if nc is not None:
        out.native_corpus = _CorpusRowView(nc, rows)
    return out


# ---------------------------------------------------------------------------
# the serializable intermediate
# ---------------------------------------------------------------------------


@dataclass
class SegmentPartial:
    """Everything the reduce needs from one segment's runs.  All content is
    iteration-keyed plain data (strings/ints — no vocabulary ids), so it
    serializes to JSON and survives vocab growth across appends.
    ``fig_files`` names the segment-owned rendered figure files cached
    alongside (restored into the report tree instead of re-rendering)."""

    iters: list[int] = field(default_factory=list)
    success_iters: list[int] = field(default_factory=list)
    failed_iters: list[int] = field(default_factory=list)
    #: per success run: ordered qualifying prototype rule tables ([] = run
    #: did not achieve the antecedent)
    proto_ordered: dict[int, list[str]] = field(default_factory=dict)
    #: per failed run: sorted distinct rule tables of its simplified
    #: consequent graph (prototype missing-list input)
    present: dict[int, list[str]] = field(default_factory=dict)
    #: per failed run: MissingEvent JSON objects (diff frontier)
    missing: dict[int, list[dict]] = field(default_factory=dict)
    #: per run: achieved-antecedent goal count (extensions gate input)
    achieved: dict[int, int] = field(default_factory=dict)
    #: anchor content (good/baseline-run derived): present on every partial
    #: whose map had the anchors in view — in practice the segment that
    #: owns the good (or baseline) run
    corrections: list[str] | None = None
    extensions: list[str] | None = None
    #: per owned run: sorted distinct extension-candidate rule tables (the
    #: batched synth kernels' per-run output, analysis/synth.py); None =
    #: the map's backend had no synthesis hooks (supports_synth False), so
    #: the reduce skips ranked repairs entirely
    ext_candidates: dict[int, list[str]] | None = None
    #: anchor content: the GOOD run's qualifying prototype rule tables —
    #: the left side of the correction anti-join, carried (like
    #: corrections) on every publishing partial; None when no good run
    #: exists or synthesis did not run
    good_proto: list[str] | None = None
    #: figure files (basenames under figures/) owned by this segment's runs
    fig_files: list[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "iters": self.iters,
            "success_iters": self.success_iters,
            "failed_iters": self.failed_iters,
            "proto_ordered": {str(k): v for k, v in self.proto_ordered.items()},
            "present": {str(k): v for k, v in self.present.items()},
            "missing": {str(k): v for k, v in self.missing.items()},
            "achieved": {str(k): v for k, v in self.achieved.items()},
            "corrections": self.corrections,
            "extensions": self.extensions,
            "ext_candidates": None
            if self.ext_candidates is None
            else {str(k): v for k, v in self.ext_candidates.items()},
            "good_proto": self.good_proto,
            "fig_files": self.fig_files,
        }

    @classmethod
    def from_json(cls, d: dict) -> "SegmentPartial":
        ext = d.get("ext_candidates")
        return cls(
            iters=[int(i) for i in d["iters"]],
            success_iters=[int(i) for i in d["success_iters"]],
            failed_iters=[int(i) for i in d["failed_iters"]],
            proto_ordered={int(k): list(v) for k, v in d["proto_ordered"].items()},
            present={int(k): list(v) for k, v in d["present"].items()},
            missing={int(k): list(v) for k, v in d["missing"].items()},
            achieved={int(k): int(v) for k, v in d["achieved"].items()},
            corrections=d.get("corrections"),
            extensions=d.get("extensions"),
            ext_candidates=None
            if ext is None
            else {int(k): list(v) for k, v in ext.items()},
            good_proto=d.get("good_proto"),
            fig_files=list(d.get("fig_files") or []),
        )


@dataclass
class MapOutput:
    """Fresh map results: the per-run artifacts of the mapped (owned) runs
    plus the in-memory DOT graphs for their figure-selected subset, keyed
    by iteration."""

    own_iters: list[int] = field(default_factory=list)
    proto_ordered: dict[int, list[str]] = field(default_factory=dict)
    present: dict[int, list[str]] = field(default_factory=dict)
    missing: dict[int, list[MissingEvent]] = field(default_factory=dict)
    achieved: dict[int, int] = field(default_factory=dict)
    corrections: list[str] = field(default_factory=list)
    extensions: list[str] = field(default_factory=list)
    #: per-run synthesis candidates (analysis/synth.py); None = the backend
    #: has no synthesis hooks, so no repairs.json will be produced
    ext_candidates: dict[int, list[str]] | None = None
    #: anchor content: the good run's qualifying prototype tables (the
    #: correction anti-join's left side); rides every map like corrections
    good_proto: list[str] | None = None
    # figure dots per family, keyed by iteration (own figure-selected runs)
    hazard: dict = field(default_factory=dict)
    pre: dict = field(default_factory=dict)
    post: dict = field(default_factory=dict)
    pre_clean: dict = field(default_factory=dict)
    post_clean: dict = field(default_factory=dict)
    diff: dict = field(default_factory=dict)
    diff_failed: dict = field(default_factory=dict)
    #: filled instead of the per-run dicts when the backend has no per-run
    #: decomposition (supports_delta False): the legacy global verb outputs
    legacy: dict | None = None

    def merge(self, other: "MapOutput") -> None:
        """Fold another map's artifacts in (the incremental checkpoint path
        maps one segment at a time, analysis/pipeline.py).  Per-run dicts
        are iteration-keyed and disjoint across segments; the anchor-verb
        results (corrections/extensions) are functions of the anchor runs,
        which ride in EVERY view, so any segment's copy is the corpus's."""
        self.own_iters.extend(other.own_iters)
        for name in (
            "proto_ordered",
            "present",
            "missing",
            "achieved",
            "hazard",
            "pre",
            "post",
            "pre_clean",
            "post_clean",
            "diff",
            "diff_failed",
        ):
            getattr(self, name).update(getattr(other, name))
        self.corrections = list(other.corrections)
        self.extensions = list(other.extensions)
        self.good_proto = other.good_proto
        if other.ext_candidates is not None:
            if self.ext_candidates is None:
                self.ext_candidates = {}
            self.ext_candidates.update(other.ext_candidates)
        if other.legacy is not None:
            self.legacy = other.legacy

    def merge_figures(self, other: "MapOutput") -> None:
        """Streamed map (ISSUE 12): fold in only what the REPORT phase
        reads — the figure DOT dicts and the mapped-run bookkeeping.  The
        per-run reduce artifacts travel exclusively in the segment
        partials (pushed into the TreeReducer as each segment completes),
        so the corpus-wide MapOutput stays O(figure-selected runs) instead
        of duplicating every per-run artifact a second time."""
        self.own_iters.extend(other.own_iters)
        for name in (
            "hazard",
            "pre",
            "post",
            "pre_clean",
            "post_clean",
            "diff",
            "diff_failed",
        ):
            getattr(self, name).update(getattr(other, name))

    def as_partial(self, seg: Segment, molly) -> SegmentPartial:
        """Slice this map's artifacts down to one segment's runs."""
        iters = [r.iteration for r in molly.runs[seg.start : seg.stop]]
        own = set(iters)
        succ = [i for i in iters if i in self.proto_ordered]
        failed = [i for i in iters if i in self.present]
        return SegmentPartial(
            iters=iters,
            success_iters=succ,
            failed_iters=failed,
            proto_ordered={i: self.proto_ordered[i] for i in succ},
            present={i: self.present[i] for i in failed},
            missing={
                i: [m.to_json() for m in self.missing[i]]
                for i in failed
                if i in self.missing
            },
            achieved={i: self.achieved[i] for i in iters if i in self.achieved},
            corrections=list(self.corrections),
            extensions=list(self.extensions),
            ext_candidates=None
            if self.ext_candidates is None
            else {i: self.ext_candidates[i] for i in iters if i in self.ext_candidates},
            good_proto=None if self.good_proto is None else list(self.good_proto),
            fig_files=[
                f
                for i in iters
                for f in figure_files_for_run(
                    i, failed=i in own and i in self.present,
                    has_diff=i in self.diff,
                    selected=i in self.hazard,
                )
            ],
        )


def figure_files_for_run(
    iteration: int, failed: bool, has_diff: bool, selected: bool
) -> list[str]:
    """The figure file basenames one selected run owns (writer.py naming).
    ``selected`` False -> none (the figure policy excluded the run)."""
    if not selected:
        return []
    fams = list(FIG_FAMILIES) + (list(DIFF_FAMILIES) if failed and has_diff else [])
    return [f"run_{iteration}_{fam}.{ext}" for fam in fams for ext in ("dot", "svg")]


# ---------------------------------------------------------------------------
# map
# ---------------------------------------------------------------------------


def map_runs(
    backend,
    molly_view,
    fault_inj_out: str,
    good_iter: int | None,
    global_fig_set: set,
    own_set: set,
    timer,
    publish: bool = True,
) -> MapOutput:
    """Run the per-run analysis verbs over ``molly_view`` (already
    init_graph_db'd into ``backend``) and extract per-run artifacts for the
    OWNED runs (``own_set``; anchor runs ride along as context only).
    ``publish`` says whether this map's output will be cached as segment
    partials — when False (cache off, anonymous corpus) the anchor verbs
    keep the reference's gates instead of computing results the reduce
    would discard.

    The phase structure and verb call order mirror the original monolithic
    run_debug exactly, so a map over the full corpus is byte- and
    dispatch-identical to the pre-split pipeline."""
    from nemo_tpu.backend.base import NoSuccessfulRunError

    out = MapOutput()
    view_iters = molly_view.get_runs_iters()
    view_failed = molly_view.get_failed_runs_iters()
    out.own_iters = [i for i in view_iters if i in own_set]

    # The view's own good-run choice must equal the global one (the view
    # always contains the global good run, and it precedes every other
    # view success in run order) — guard the invariant rather than trust it.
    try:
        view_good = backend.good_run_iter()
    except NoSuccessfulRunError:
        view_good = None
    if view_good != good_iter:
        raise RuntimeError(
            f"segment view chose good run {view_good!r} but the corpus good "
            f"run is {good_iter!r}; the anchor run must be part of the view"
        )

    fig_iters = [i for i in view_iters if i in global_fig_set]
    fig_set = set(fig_iters)
    fig_failed = [f for f in view_failed if f in fig_set]
    own_fig = [i for i in fig_iters if i in own_set]
    own_fig_failed = [f for f in fig_failed if f in own_set]
    own_failed = [f for f in view_failed if f in own_set]

    with timer.phase("load_raw_provenance"):
        backend.load_raw_provenance()
    with timer.phase("simplify"):
        backend.simplify_prov(view_iters)
    with timer.phase("hazard"):
        hazard_dots = backend.create_hazard_analysis(fault_inj_out, own_fig)
    out.hazard = dict(zip(own_fig, hazard_dots))

    view_succ = molly_view.get_success_runs_iters()
    own_succ = [i for i in view_succ if i in own_set]
    legacy = not getattr(backend, "supports_delta", False)
    with timer.phase("prototypes"):
        if legacy:
            inter, inter_miss, union, union_miss = backend.create_prototypes(
                view_succ, view_failed
            )
        else:
            ordered, present = backend.proto_tables_by_run(own_succ, own_failed)
            out.proto_ordered = {i: list(ordered.get(i, [])) for i in own_succ}
            out.present = {f: sorted(present.get(f, ())) for f in own_failed}

    # The good run's post dot is the diff overlay's backdrop; pull it even
    # when the good run belongs to a cached segment (context, not output).
    pull_iters = list(own_fig)
    if good_iter is not None and good_iter in fig_set and good_iter not in own_set:
        pull_iters = sorted(
            set(pull_iters) | {good_iter}, key=view_iters.index
        )
    with timer.phase("pull_prov"):
        pre_dots, post_dots, pre_clean_dots, post_clean_dots = (
            backend.pull_pre_post_prov(pull_iters)
        )
    by = dict(zip(pull_iters, zip(pre_dots, post_dots, pre_clean_dots, post_clean_dots)))
    for i in own_fig:
        out.pre[i], out.post[i], out.pre_clean[i], out.post_clean[i] = by[i]

    missing_events: list[list[MissingEvent]] = [[] for _ in own_failed]
    diff_dots: list = []
    failed_dots: list = []
    if good_iter is not None and own_failed:
        success_post_dot = (
            by[good_iter][1] if good_iter in by else None
        )
        with timer.phase("diff_prov"):
            diff_dots, failed_dots, missing_events = backend.create_naive_diff_prov(
                False, own_failed, success_post_dot, dot_iters=own_fig_failed
            )
    out.missing = dict(zip(own_failed, missing_events))
    out.diff = dict(zip(own_fig_failed, diff_dots))
    out.diff_failed = dict(zip(own_fig_failed, failed_dots))

    # Anchor content is computed UNGATED on the PUBLISHING delta path
    # (cached partials must carry corrections even when the current corpus
    # has no failures — a grown corpus may gain its first failure in a NEW
    # segment and merge the old anchor partial); when nothing will be
    # cached, and on the legacy monolithic path, the reference's failures
    # gate applies (main.go:166-173) — the reduce discards the results in
    # exactly those cases, so computing them would be pure waste.
    if good_iter is not None and (view_failed or (publish and not legacy)):
        with timer.phase("corrections"):
            out.corrections = backend.generate_corrections()
    with timer.phase("extensions"):
        if legacy:
            all_achieved, extensions = backend.generate_extensions()
            out.legacy = {
                "inter": inter,
                "inter_miss": dict(zip(view_failed, inter_miss)),
                "union": union,
                "union_miss": dict(zip(view_failed, union_miss)),
                "all_achieved": all_achieved,
                "extensions": extensions,
            }
        else:
            counts = backend.achieved_pre_goal_counts()
            out.achieved = {i: int(counts.get(i, 0)) for i in out.own_iters}
            # A non-publishing map is the WHOLE corpus (nothing was
            # cached), so the local achieved sum decides the reduce's
            # all-achieved gate — skip the suggestion synthesis it would
            # discard.
            if publish or sum(out.achieved.values()) < len(view_iters):
                out.extensions = backend.extension_suggestions()

    # Corpus-ranked repair synthesis (ISSUE 13): per-run extension
    # candidates via the batched synth kernels, plus the good run's
    # prototype table set (the correction anti-join's left side) as ANCHOR
    # content — the good run rides in every view, so every publishing
    # partial carries the same copy (the corrections convention, which is
    # what keeps the tree merge order-insensitive).  Ungated: repairs.json
    # is part of every report this backend family produces.
    if not legacy and getattr(backend, "supports_synth", False):
        with timer.phase("synthesis"):
            ext = backend.synth_candidates(out.own_iters)
            out.ext_candidates = {i: list(ext.get(i, [])) for i in out.own_iters}
            if good_iter is not None:
                g_ordered, _g_present = backend.proto_tables_by_run([good_iter], [])
                out.good_proto = list(g_ordered.get(good_iter, []))
    return out


# ---------------------------------------------------------------------------
# reduce
# ---------------------------------------------------------------------------


def reduce_arity() -> int:
    """Merge arity of the tree reduce (``NEMO_REDUCE_ARITY``, default 8,
    floor 2): reduce state stays bounded at O(arity * log_arity(S)) live
    partials instead of accumulating all S."""
    from nemo_tpu.utils.env import env_int

    return max(2, env_int("NEMO_REDUCE_ARITY", 8))


def merge_partials(parts: "list[SegmentPartial]", arity: int | None = None) -> "SegmentPartial":
    """Associatively merge segment partials into ONE partial, as a k-ary
    TREE (pairwise at arity=2) rather than a flat fold — the shape that
    keeps the reduce state O(log S) deep and lets the run axis shard.

    Per-run dicts are iteration-keyed and disjoint across segments (dict
    union); the anchor content (corrections/extensions) is a function of
    the good/baseline runs, which ride in every publishing map's view, so
    every carrier holds the SAME values — the merge keeps the later
    carrier's copy, exactly the flat left-fold's last-wins, making tree
    and flat byte-equal for any arity and segment count (the property
    test in tests/test_delta.py pins this)."""
    if not parts:
        return SegmentPartial()
    k = arity or reduce_arity()
    items = list(parts)
    while len(items) > 1:
        items = [_merge_group(items[i : i + k]) for i in range(0, len(items), k)]
        obs.metrics.inc("delta.tree_merge_levels")
    return items[0]


def _merge_group(group: "list[SegmentPartial]") -> "SegmentPartial":
    """One merge node: fold a <=arity group of partials left to right."""
    if len(group) == 1:
        return group[0]
    out = SegmentPartial()
    for p in group:
        out.iters.extend(p.iters)
        out.success_iters.extend(p.success_iters)
        out.failed_iters.extend(p.failed_iters)
        out.proto_ordered.update(p.proto_ordered)
        out.present.update(p.present)
        out.missing.update(p.missing)
        out.achieved.update(p.achieved)
        out.fig_files.extend(p.fig_files)
        if p.ext_candidates is not None:
            if out.ext_candidates is None:
                out.ext_candidates = {}
            out.ext_candidates.update(p.ext_candidates)
        if p.corrections is not None:
            # Coupled move: the flat fold takes extensions (and the
            # good-run prototype anchor, ISSUE 13) from the SAME partial
            # that supplied corrections.
            out.corrections = list(p.corrections)
            out.extensions = list(p.extensions or [])
            out.good_proto = None if p.good_proto is None else list(p.good_proto)
    obs.metrics.inc("delta.tree_merges")
    return out


class TreeReducer:
    """Incremental tree merge for the STREAMED reduce: partials are pushed
    as their segments finish mapping and fold binary-counter style — level
    0 buffers up to ``arity`` partials, a full buffer merges into one
    level-1 partial, and so on — so at any moment at most
    ``arity * ceil(log_arity(S))`` partials are live regardless of how many
    segments streamed through.  ``partials()`` returns the live frontier in
    push order (deepest level first), which :func:`reduce_partials`
    finishes — byte-equal to reducing the full flat list."""

    def __init__(self, arity: int | None = None) -> None:
        self.arity = arity or reduce_arity()
        self._levels: list[list[SegmentPartial]] = []
        self.pushed = 0

    def push(self, p: "SegmentPartial") -> None:
        self.pushed += 1
        lvl = 0
        while True:
            if len(self._levels) <= lvl:
                self._levels.append([])
            buf = self._levels[lvl]
            buf.append(p)
            if len(buf) < self.arity:
                return
            p = _merge_group(buf)
            self._levels[lvl] = []
            lvl += 1

    def live(self) -> int:
        return sum(len(b) for b in self._levels)

    def partials(self) -> "list[SegmentPartial]":
        """The live frontier, chronological (a level-N item was always
        pushed before any surviving lower-level item)."""
        out: list[SegmentPartial] = []
        for lvl in reversed(range(len(self._levels))):
            out.extend(self._levels[lvl])
        return out


class _JsonEvent:
    """MissingEvent stand-in rehydrated from a cached partial: only its
    ``to_json`` is ever consumed downstream (debugging.json splicing), so
    the stored JSON is carried verbatim."""

    __slots__ = ("_doc",)

    def __init__(self, doc: dict) -> None:
        self._doc = doc

    def to_json(self) -> dict:
        return self._doc


@dataclass
class Reduced:
    """Global analysis results, ready for recommendation assembly."""

    inter: list[str]  # <code>-wrapped
    union: list[str]
    inter_miss: dict[int, list[str]]  # per failed iteration
    union_miss: dict[int, list[str]]
    missing: dict[int, list]  # per failed iteration: MissingEvent-likes
    corrections: list[str]
    extensions: list[str]
    all_achieved: bool
    #: corpus-ranked repair document (analysis/synth.py:build_repairs —
    #: repairs.json); None when the backend has no synthesis hooks
    repairs: dict | None = None


def reduce_partials(
    partials: list[SegmentPartial],
    molly,
    good_iter: int | None,
    legacy: dict | None = None,
) -> Reduced:
    """Merge per-segment partials into the corpus-global analysis results.

    Order-insensitive by construction: every per-run artifact is keyed by
    iteration and the GLOBAL run order is imposed here from ``molly`` —
    merging [seg0, seg1] equals merging [seg1, seg0], which is what lets a
    grown corpus combine cached and fresh partials (and what a future
    run-axis shard needs).  The set algebra is analysis/protos.py — the
    same functions every backend's create_prototypes uses, so a one-segment
    reduce is bit-identical to the monolithic pipeline."""
    from nemo_tpu.analysis.protos import (
        intersect_proto,
        missing_from,
        union_proto,
        wrap_code,
    )

    failed_iters = molly.get_failed_runs_iters()
    success_iters = molly.get_success_runs_iters()

    if legacy is not None:
        # Backend without per-run decomposition (supports_delta False): the
        # map ran the global verbs over the whole corpus; pass their
        # results through, with the per-failed-run missing events merged
        # from the (single) partial like the mergeable path below.
        missing = {f: [] for f in failed_iters}
        corrections = []
        for p in partials:
            for f, docs in p.missing.items():
                missing[f] = [
                    d if isinstance(d, MissingEvent) else _JsonEvent(d)
                    for d in docs
                ]
            if p.corrections is not None:
                corrections = list(p.corrections)
        return Reduced(
            inter=legacy["inter"],
            union=legacy["union"],
            inter_miss=dict(legacy["inter_miss"]),
            union_miss=dict(legacy["union_miss"]),
            missing=missing,
            corrections=corrections if (good_iter is not None and failed_iters) else [],
            # generate_extensions already applied the all-achieved gate.
            extensions=legacy["extensions"],
            all_achieved=legacy["all_achieved"],
        )

    with obs.span("analysis:reduce", segments=len(partials)):
        # Sharded TREE merge (ISSUE 12): pairwise/k-ary instead of a flat
        # left-fold, so the merge state stays O(arity * log S) and the same
        # associative node serves the streamed reducer (TreeReducer).
        merged = merge_partials(partials)
        ordered = merged.proto_ordered
        present = merged.present
        missing: dict[int, list] = {
            f: [d if isinstance(d, MissingEvent) else _JsonEvent(d) for d in docs]
            for f, docs in merged.missing.items()
        }
        achieved_total = sum(merged.achieved.values())
        corrections = list(merged.corrections or [])
        extensions = list(merged.extensions or [])
        anchor_seen = merged.corrections is not None
        if not anchor_seen and molly.runs:
            raise RuntimeError(
                "no partial carried the anchor (good/baseline) results; "
                "the anchor segment must be mapped or served from cache"
            )

        per_run = [ordered.get(i, []) for i in success_iters]
        inter_raw = intersect_proto(per_run, "post")
        union_raw = union_proto(per_run, "post")
        inter_miss: dict[int, list[str]] = {}
        union_miss: dict[int, list[str]] = {}
        for f in failed_iters:
            ptab = set(present.get(f, ()))
            inter_miss[f] = missing_from(inter_raw, ptab)
            union_miss[f] = missing_from(union_raw, ptab)
            missing.setdefault(f, [])

        all_achieved = achieved_total >= len(molly.runs)
        # Corpus-ranked repairs (ISSUE 13): the order-insensitive
        # support-count reduce over the merged per-run candidate dicts —
        # global run order imposed by build_repairs from `molly`, so any
        # partial permutation ranks identically.
        repairs = None
        if merged.ext_candidates is not None:
            from nemo_tpu.analysis.synth import build_repairs

            repairs = build_repairs(
                merged.good_proto, merged.ext_candidates, present, molly, good_iter
            )
        return Reduced(
            inter=wrap_code(inter_raw),
            union=wrap_code(union_raw),
            inter_miss=inter_miss,
            union_miss=union_miss,
            missing=missing,
            # The reference gates corrections on failures existing and a
            # good run to diff against (main.go:166-173); extension
            # suggestions only apply when some run missed the antecedent.
            corrections=corrections if (good_iter is not None and failed_iters) else [],
            extensions=[] if all_achieved else extensions,
            all_achieved=all_achieved,
            repairs=repairs,
        )


def blob_cache_key(kind: str, segments_meta, extra: dict) -> str | None:
    """Content address for an opaque result blob derived from a stored
    corpus (e.g. the sidecar's AnalyzeDir response): every segment
    fingerprint + a caller-supplied extra dict (statics, wire version) +
    the analysis ABI.  None when the corpus carries no segment identities
    (not store-served) — nothing content-addresses it."""
    fps = [s.get("fingerprint") for s in segments_meta or ()]
    if not fps or any(f is None for f in fps):
        return None
    return _key(
        {
            "kind": kind,
            "segments": fps,
            "extra": extra,
            "analysis_abi": ANALYSIS_ABI_VERSION,
        }
    )


# ---------------------------------------------------------------------------
# metrics helpers
# ---------------------------------------------------------------------------


def kernel_dispatch_count(counters: dict) -> int:
    """Total analysis kernel dispatches in a metrics counter dict — device
    executor verbs AND the sparse host engine (kernel.dispatches.*).  The
    zero-dispatch assertion of a warm cache hit sums exactly this."""
    return int(
        sum(v for k, v in counters.items() if k.startswith("kernel.dispatches."))
    )
