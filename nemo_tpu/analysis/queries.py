"""Host-side PGraph pattern queries shared by backends.

The trigger patterns behind corrections/extensions only ever run on run 0's
raw provenance (corrections.go:210-216, extensions.go:63-67) — they are O(one
small graph) host work, not batch workloads — so both backends share these
free functions; the JAX backend feeds them PGraphs whose condition_holds came
from the device kernels.
"""

from __future__ import annotations

from nemo_tpu.graphs.pgraph import PGraph
from nemo_tpu.ingest.datatypes import Goal, Rule

from .corrections import PostTrigger, PreTrigger, parse_receiver


def _goal_of(node, receiver: bool = True) -> Goal:
    return Goal(
        id=node.id,
        label=node.label,
        table=node.table,
        time=node.time,
        cond_holds=node.cond_holds,
        receiver=parse_receiver(node.label, node.table) if receiver else "",
    )


def _rule_of(node) -> Rule:
    return Rule(id=node.id, label=node.label, table=node.table, type=node.type)


def find_pre_triggers(g: PGraph) -> list[PreTrigger]:
    """(a:Rule)->(g:Goal !holds)->(r:Rule) with a holding goal above a
    (reference: corrections.go:30-34), in node/edge order."""
    out = []
    for a in g.nodes.values():
        if a.is_goal:
            continue
        if not any(g.nodes[p].is_goal and g.nodes[p].cond_holds for p in g.inn[a.id]):
            continue
        for gid in g.out[a.id]:
            goal = g.nodes[gid]
            if not goal.is_goal or goal.cond_holds:
                continue
            for rid in g.out[gid]:
                rule = g.nodes[rid]
                if rule.is_goal:
                    continue
                out.append(PreTrigger(agg=_rule_of(a), goal=_goal_of(goal), rule=_rule_of(rule)))
    return out


def find_post_triggers(g: PGraph) -> list[PostTrigger]:
    """(g:Goal holds)->(r:Rule) with a rule above g and a non-holding goal
    below r that itself has a rule below (reference: corrections.go:121-125)."""
    out = []
    for goal in g.nodes.values():
        if not goal.is_goal or not goal.cond_holds:
            continue
        if not any(not g.nodes[p].is_goal for p in g.inn[goal.id]):
            continue
        for rid in g.out[goal.id]:
            rule = g.nodes[rid]
            if rule.is_goal:
                continue
            qualifies = any(
                g.nodes[c].is_goal
                and not g.nodes[c].cond_holds
                and any(not g.nodes[cr].is_goal for cr in g.out[c])
                for c in g.out[rid]
            )
            if qualifies:
                out.append(PostTrigger(goal=_goal_of(goal), rule=_rule_of(rule)))
    return out


def extension_candidates(g: PGraph) -> list[str]:
    """Async rules adjacent to the antecedent's condition boundary:
    (holding goal)->r->(non-holding goal)->(rule) OR (non-holding goal)->r
    (reference: extensions.go:63-67).  Returns rule tables (with repeats)."""
    candidates = []
    for r in g.nodes.values():
        if r.is_goal or r.type != "async":
            continue
        cond_a = any(
            g.nodes[p].is_goal and g.nodes[p].cond_holds for p in g.inn[r.id]
        ) and any(
            g.nodes[c].is_goal
            and not g.nodes[c].cond_holds
            and any(not g.nodes[cr].is_goal for cr in g.out[c])
            for c in g.out[r.id]
        )
        cond_b = any(g.nodes[p].is_goal and not g.nodes[p].cond_holds for p in g.inn[r.id])
        if cond_a or cond_b:
            candidates.append(r.table)
    return candidates
