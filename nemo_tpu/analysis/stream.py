"""Out-of-core segment-streamed analysis (ISSUE 12).

The PR-5 append-only segment store and the PR-6 per-segment map/reduce are
exactly the out-of-core shape; this module makes streaming them the
engine's default scaling mode.  Instead of mapping a corpus in one sweep
over consolidated arrays, the store's segments flow through the mesh one
at a time behind a **double-buffered host->device prefetch pipeline**:

  * a background thread STAGES segment k+1 — builds its row-subset view
    straight from the per-segment mmaps (store/reader.py:LazyCondBatch.take,
    so the corpus-wide planes never materialize), initializes a per-segment
    backend clone, bucketizes the fused inputs, and ``jax.device_put``s the
    narrowed planes where a real accelerator backs the platform
    (JaxBackend.stage_fused_inputs) —
  * while segment k's dispatches drain on the consuming thread.

A bounded in-flight budget (``NEMO_STREAM_SEGMENTS``, default 2) keeps at
most that many segments resident, so ingest never starves the accelerators
and never outruns memory: peak RSS is O(segment + reduce state),
independent of corpus size.  Each segment's partial drops to the result
cache as soon as it reduces (the PR-9 checkpoint path — streamed runs are
crash-resumable for free) and its arrays are released; the reduce itself
is the k-ary TREE merge (analysis/delta.py:TreeReducer), bounded at
O(arity * log S) live partials.

Byte parity: per-run artifacts are independent of batch composition (the
sparse/dense parity suites pin this) and the reduce is order-insensitive
(PR 6), so a streamed report is byte-identical to the in-memory one —
``make stream-smoke`` asserts exactly that, plus a strictly lower RSS
watermark and SIGKILL-resume.

Knobs:

  NEMO_STREAM           auto (default) | on/1 | off/0.  auto streams any
                        store-served corpus with >=2 segments to map on a
                        stream-capable backend; on forces (warns and falls
                        back when the corpus/backend cannot stream); off
                        restores the in-memory sweep.
  NEMO_STREAM_SEGMENTS  in-flight segment budget (default 2 = classic
                        double buffering: one analyzing + one staging).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

from nemo_tpu import obs

_log = obs.log.get_logger("nemo.stream")

_SENTINEL = object()


def stream_env() -> str:
    """``NEMO_STREAM``: auto | on | off (1/0 accepted)."""
    from nemo_tpu.utils.env import env_choice

    got = env_choice("NEMO_STREAM", "auto", ("auto", "on", "1", "off", "0"))
    return {"1": "on", "0": "off"}.get(got, got)


def stream_budget() -> int:
    """``NEMO_STREAM_SEGMENTS``: how many segments may be resident at once
    (the one analyzing + those staged ahead).  Default 2 — classic double
    buffering; 1 degenerates to stage-then-analyze with no overlap but
    still the bounded per-segment working set."""
    from nemo_tpu.utils.env import env_int

    return max(1, env_int("NEMO_STREAM_SEGMENTS", 2))


def use_streaming(molly, backend, to_map, legacy: bool = False) -> bool:
    """Whether this run's map streams segment-by-segment.

    Capability needs: a per-run-decomposing backend that can clone itself
    for background staging (GraphBackend.stream_clone), a packed corpus
    (the row-subset views are array gathers), and >=2 segments left to map
    (a single segment IS the bounded working set already).  ``on`` without
    capability warns and falls back — never silently wrong bytes, never a
    hard failure for a knob that only changes the execution shape."""
    mode = stream_env()
    if mode == "off":
        return False
    capable = (
        not legacy
        and len(to_map) >= 2
        and getattr(molly, "native_corpus", None) is not None
        and backend.stream_clone() is not None
    )
    if mode == "on" and not capable:
        obs.metrics.inc("stream.unstreamable")
        _log.warning(
            "stream.unstreamable",
            detail="NEMO_STREAM=on but this run cannot stream "
            "(object-loader corpus, non-cloning backend, or <2 segments "
            "to map); running the in-memory sweep",
            segments_to_map=len(to_map),
        )
        return False
    return capable


def stream_peak_rss_bytes() -> int:
    """Current process peak RSS in bytes (ru_maxrss is KB on Linux)."""
    import resource
    import sys

    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(peak * (1 if sys.platform == "darwin" else 1024))


def note_segment_done() -> None:
    """Per-segment RSS watermark gauge (``mem.stream_peak_rss``): the
    stream-smoke and the bench stream tier read this to prove the working
    set stays bounded as segments flow through."""
    obs.metrics.gauge("mem.stream_peak_rss", stream_peak_rss_bytes())


@dataclass
class StagedGroup:
    """One staged map group: the row-subset view, its own-run set, and the
    per-segment backend (already init_graph_db'd, fused inputs staged)."""

    group: list
    view: object
    own_set: set
    backend: object
    stage_s: float = 0.0
    staged_bytes: int = 0
    #: serial-path marker: the shared caller-owned backend rides here, and
    #: release() must not drop state the next group needs.
    shared_backend: bool = field(default=False)
    #: residency-slot release (the stream budget's semaphore); None on the
    #: serial/inline paths.
    _slot: object = field(default=None, repr=False)

    def release(self) -> None:
        """Drop the segment's array references so its working set frees as
        soon as the map completes (the backend was close_db'd by the
        caller), and return the residency slot to the prefetcher — the
        budget counts a segment as resident until exactly here."""
        self.view = None
        self.own_set = None
        if not self.shared_backend:
            self.backend = None
        slot, self._slot = self._slot, None
        if slot is not None:
            slot.release()


def stream_groups(
    map_groups,
    build_view,
    backend,
    conn: str,
    timer=None,
    budget: int | None = None,
    threaded: bool | None = None,
):
    """Generator over :class:`StagedGroup`s with double-buffered prefetch.

    ``build_view(group) -> (molly_view, own_set)`` runs on the staging
    side; a background thread stages ahead of the consumer under a
    residency budget of ``budget`` segments — a slot is held from before a
    segment's staging starts until ``StagedGroup.release()`` — so segment
    k+1's store load + bucketize + device staging overlaps segment k's
    dispatch drain without ever exceeding the bound.  On an effectively 1-core host the thread is
    skipped (a producer cannot overlap the consumer on one core — the
    run_debug_dirs precedent) and staging runs inline, preserving the
    bounded working set without the handoff overhead.

    Consumer-side stalls (the accelerator waiting on ingest) are recorded
    as ``stream.prefetch_stall_s`` and — when ``timer`` is passed — as the
    ``stream_wait`` pipeline phase, so the overlap fraction is measurable.
    """
    budget = budget or stream_budget()
    if threaded is None:
        from nemo_tpu.utils import effective_cpu_count

        threaded = effective_cpu_count() > 1

    def stage(group) -> StagedGroup:
        t0 = time.perf_counter()
        with obs.span(
            "analysis:stream_prefetch",
            segments=len(group),
            runs=sum(s.n_runs for s in group),
        ):
            view, own_set = build_view(group)
            seg_backend = backend.stream_clone()
            seg_backend.init_graph_db(conn, view)
            staged_bytes = 0
            stage_inputs = getattr(seg_backend, "stage_fused_inputs", None)
            if stage_inputs is not None:
                plan = stage_inputs()
                staged_bytes = int(plan.get("staged_bytes") or 0)
        dt = time.perf_counter() - t0
        obs.metrics.observe("stream.stage_s", dt)
        obs.metrics.inc("stream.segments_staged")
        if staged_bytes:
            obs.metrics.inc("stream.staged_bytes", staged_bytes)
        return StagedGroup(
            group=group,
            view=view,
            own_set=own_set,
            backend=seg_backend,
            stage_s=dt,
            staged_bytes=staged_bytes,
        )

    # Whether the prefetch actually ran on a background thread — the bench
    # reads this to report a 0 overlap fraction on 1-core hosts instead of
    # a vacuous "no stalls" 1.0 (inline staging serializes with compute).
    obs.metrics.gauge("stream.threaded", int(bool(threaded)))
    if not threaded:
        obs.metrics.gauge("stream.segments_inflight", 1)
        for group in map_groups:
            yield stage(group)
        obs.metrics.gauge("stream.segments_inflight", 0)
        return

    q: "queue.Queue" = queue.Queue()
    stop = threading.Event()
    # The residency budget: a segment holds a slot from BEFORE its staging
    # starts until StagedGroup.release() — so at most `budget` segments'
    # arrays exist at any moment (the one analyzing + those staged ahead),
    # not budget+1 (a producer that staged first and only then blocked on a
    # bounded queue would be holding an extra resident segment while
    # parked).
    slots = threading.Semaphore(budget)

    def put(item) -> None:
        # The queue itself is unbounded — the semaphore is the bound — so
        # puts never park; only slot acquisition waits, and it stays
        # responsive to consumer abandonment via `stop`.
        q.put(item)

    def producer() -> None:
        try:
            for group in map_groups:
                while not slots.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                staged = stage(group)
                staged._slot = slots
                put(staged)
            put(_SENTINEL)
        except BaseException as ex:  # re-raised on the consuming thread
            put(ex)

    th = threading.Thread(target=producer, daemon=True, name="nemo-stream-prefetch")
    th.start()
    try:
        while True:
            t0 = time.perf_counter()
            if timer is not None:
                with timer.phase("stream_wait"):
                    item = q.get()
            else:
                item = q.get()
            obs.metrics.inc("stream.prefetch_stall_s", time.perf_counter() - t0)
            if item is _SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            # Staged-ahead + the one just handed over.
            obs.metrics.gauge("stream.segments_inflight", q.qsize() + 1)
            yield item
        obs.metrics.gauge("stream.segments_inflight", 0)
    finally:
        stop.set()
