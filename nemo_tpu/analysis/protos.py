"""Prototype set algebra shared by all backends.

Backends produce, per achieving run, an ordered list of rule tables (the
"skeleton" of how the consequent was derived); this module computes the
intersection- and union-prototypes and per-failed-run missing lists
(reference: graphing/prototype.go:80-130, :141-206).
"""

from __future__ import annotations


def intersect_proto(per_run_tables: list[list[str]], condition: str) -> list[str]:
    """Rule tables present in EVERY condition-achieving run.

    Mirrors prototype.go:80-109: iterate the first run's list in order, keep
    entries found in all non-empty (achieving) lists, excluding the condition
    table itself.  Empty first list -> empty result (also mirrored).
    """
    achieving = [t for t in per_run_tables if t]
    if not achieving:
        return []
    first = achieving[0]
    rest = achieving[1:]
    out = []
    for table in first:
        if table == condition:
            continue
        if all(table in other for other in rest):
            out.append(table)
    return out


def union_proto(per_run_tables: list[list[str]], condition: str) -> list[str]:
    """All rule tables seen in any achieving run, interleaved positionally in
    first-seen order (prototype.go:112-130): position 0 of every run, then
    position 1, ..., skipping duplicates and the condition table."""
    achieving = [t for t in per_run_tables if t]
    if not achieving:
        return []
    longest = max(len(t) for t in achieving)
    seen: set[str] = set()
    out: list[str] = []
    for pos in range(longest):
        for tables in achieving:
            if pos < len(tables):
                table = tables[pos]
                if table != condition and table not in seen:
                    seen.add(table)
                    out.append(table)
    return out


def missing_from(proto: list[str], present_tables: set[str]) -> list[str]:
    """Prototype entries absent from a failed run's rule tables, wrapped in
    <code> for the report (prototype.go:189-197)."""
    return [f"<code>{t}</code>" for t in proto if t not in present_tables]


def wrap_code(items: list[str]) -> list[str]:
    """Presentation wrapper applied to final prototypes (prototype.go:245-251)."""
    return [f"<code>{t}</code>" for t in items]
