"""Correction and extension string synthesis, shared by all backends.

Backends extract trigger tuples from run 0's provenance (by Cypher-equivalent
pattern matching); this module turns them into the presentation-ready HTML
recommendation strings, format-identical to the reference
(graphing/corrections.go:202-328, graphing/extensions.go:13-99).

Determinism: the reference iterates Go maps here, so its output order is
nondeterministic (and its maps are keyed by pointer, so same-table triggers
are never actually merged).  Canonical order in this rebuild: aggregation
tables sorted; triggers of one aggregation in provenance edge order;
consequent triggers sorted by (receiver, table).
"""

from __future__ import annotations

from dataclasses import dataclass

from nemo_tpu.ingest.datatypes import Goal, Rule


def parse_receiver(label: str, table: str) -> str:
    """First argument of a goal label, e.g. 'log(b, foo)' -> 'b'.

    The reference trims the label with the table name as a TrimLeft *cutset*
    then splits on ', ' (corrections.go:65-67); this strips the table as a
    proper prefix instead, which agrees on every well-formed label and avoids
    over-trimming when an argument starts with a letter of the table name.
    """
    rest = label[len(table):] if label.startswith(table) else label
    rest = rest.strip("()")
    parts = rest.split(", ")
    return parts[0] if parts else ""


@dataclass
class PreTrigger:
    """One antecedent trigger chain: aggregation rule just below a holding
    goal, the non-holding goal under it, and that goal's rule
    (reference: corrections.go:30-34, the (a)->(g)->(r) match)."""

    agg: Rule
    goal: Goal
    rule: Rule


@dataclass
class PostTrigger:
    """One consequent trigger pair: holding non-root goal and the rule below
    it that leads to a non-holding goal (reference: corrections.go:121-125)."""

    goal: Goal
    rule: Rule


def synthesize_corrections(
    pre_triggers: list[PreTrigger], post_triggers: list[PostTrigger]
) -> list[str]:
    """Build correction recommendations (reference: corrections.go:202-328).

    For each antecedent aggregation table: reconstruct its trigger clause; if
    all consequent triggers fire on the same node, append their tables to the
    antecedent body (local order suffices); otherwise synthesize an
    ack_<rule>@async message round per differing consequent trigger and a
    buffer_<rule>@next persistence scheme per non-next antecedent trigger,
    ending with the old=>new rule rewrite.
    """
    recs: list[str] = []

    # Group pre triggers by aggregation table, preserving extraction order.
    by_table: dict[str, list[PreTrigger]] = {}
    for t in pre_triggers:
        by_table.setdefault(t.agg.table, []).append(t)

    posts = sorted(post_triggers, key=lambda p: (p.goal.receiver, p.goal.table))

    for agg_table in sorted(by_table):
        triggers = by_table[agg_table]

        # Compound trigger clause (corrections.go:231-243).
        clause = ""
        for t in triggers:
            if not clause:
                clause = (
                    f"{agg_table}({t.goal.receiver}, ...) :- "
                    f"{t.rule.table}({t.goal.receiver}, ...)"
                )
            else:
                clause = f"{clause}, {t.rule.table}({t.goal.receiver}, ...)"

        # Consequent triggers on a different node than a pre trigger goal
        # force a communication round (corrections.go:245-259).
        differing = [
            (t, p)
            for t in triggers
            for p in posts
            if t.goal.receiver != p.goal.receiver
        ]

        agg_new = clause
        if not differing:
            # Same node everywhere: local order suffices (corrections.go:264-272).
            for p in posts:
                agg_new = f"{agg_new}, {p.goal.table}({p.goal.receiver}, ...)"
        else:
            # Message round per (pre node, post trigger) pair (corrections.go:279-294).
            seen_pairs: set[tuple[str, str, str]] = set()
            for t, p in differing:
                pre_node = t.goal.receiver
                post_node = p.goal.receiver
                post_rule = p.goal.table
                key = (pre_node, post_node, post_rule)
                if key in seen_pairs:
                    continue
                seen_pairs.add(key)
                recs.append(
                    f"<code>{pre_node}</code> needs to know that <code>{post_node}</code> "
                    f"has executed <code>{post_rule}</code>. Add:<br /> &nbsp; &nbsp; "
                    f"&nbsp; &nbsp; <code>ack_{post_rule}({pre_node}, ...)@async :- "
                    f"{post_rule}({post_node}, ...), ...;</code>"
                )
                agg_new = f"{agg_new}, ack_{post_rule}({pre_node}, sender={post_node}, ...)"

            # Persistence scheme for one-shot antecedent triggers
            # (corrections.go:297-317).
            for t in triggers:
                if t.rule.type != "next":
                    rule, node = t.rule.table, t.goal.receiver
                    recs.append(
                        "Antecedent depends on timing of an onetime event. Make it "
                        "persistent. Add:<br /> &nbsp; &nbsp; &nbsp; &nbsp; "
                        f"<code>buffer_{rule}({node}, ...) :- {rule}({node}, ...), ...;"
                        "</code><br /> &nbsp; &nbsp; &nbsp; &nbsp; "
                        f"<code>buffer_{rule}({node}, ...)@next :- buffer_{rule}({node}, "
                        "...), ...;"
                    )
                    agg_new = agg_new.replace(
                        f"{rule}({node}, ...)", f"buffer_{rule}({node}, ...)"
                    )

        recs.append(
            f"Change: <code>{clause};</code> &nbsp; "
            '<i class = "fas fa-long-arrow-alt-right"></i> &nbsp; '
            f"<code>{agg_new};</code>"
        )

    return recs


def synthesize_extensions(async_rule_tables: list[str]) -> list[str]:
    """One hardening suggestion per distinct async rule table adjacent to the
    antecedent's condition boundary (reference: extensions.go:77-90), sorted."""
    return [
        f"<code>{table}(node, ...)@async :- ...;</code>"
        for table in sorted(set(async_rule_tables))
    ]
