"""Device topology + run-axis sharding: THE mesh module (ISSUE 7).

The run axis is the framework's data-parallel axis (SURVEY.md §2.3): the
reference analyzes runs in a sequential host loop; here the packed run batch
is sharded over a 1-D `jax.sharding.Mesh` and the same jitted analysis_step
runs SPMD, with the cross-run prototype reductions (jnp.all/any over the run
axis) lowered by XLA to all-reduces over ICI.  Multi-host scale-out uses the
same code path — jax.distributed + a larger mesh — with DCN only between
hosts, never inside the per-run kernels.

This module is the single source of truth for device topology: every mesh
the repo builds — the production run mesh (`make_run_mesh`, consumed by the
sharded fused dispatch in backend/jax_backend.py:LocalExecutor), the
node-sharded giant/ring mesh (`make_node_mesh`, re-exported by
parallel/ring.py), and the multi-host hybrid DCN x ICI grid
(`make_hybrid_mesh`, re-exported by parallel/distributed.py) — derives its
device list from one `device_grid` helper, so a future multi-host layout
changes exactly one place.

Production knobs (the NEMO_SHARD_* family, see also parallel/sched.py):

  NEMO_SHARD=auto|1|0    run-axis sharding of the fused dispatch: auto
                         (default) shards whenever >1 device is visible;
                         0 pins the single-device dispatch; 1 forces the
                         mesh path even on one device (a no-op placement,
                         kept dispatchable for parity tests).
  NEMO_SHARD_DEVICES=N   cap the run mesh at the first N devices
                         (default: all visible devices).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nemo_tpu.models.pipeline_model import BatchArrays, analysis_step

RUN_AXIS = "run"
NODE_AXIS = "node"
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def device_grid(n_devices: int | None = None, shape: tuple | None = None) -> np.ndarray:
    """The validated device array every mesh constructor builds on: the
    first `n_devices` visible devices (default all), reshaped to `shape`
    (default 1-D).  Raises — rather than silently truncating — when the
    request exceeds the visible device count or the grid would drop
    devices from the requested slice."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"need {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n])
    if shape is not None:
        if int(np.prod(shape)) != n:
            raise ValueError(f"grid shape {shape} does not cover {n} devices")
        grid = grid.reshape(shape)
    return grid


def make_run_mesh(n_devices: int | None = None) -> Mesh:
    """The production 1-D run-axis mesh (SNIPPETS [2]'s "batch" mesh, with
    this repo's axis name)."""
    grid = device_grid(n_devices)
    return Mesh(grid, (RUN_AXIS,))


def make_node_mesh(n_devices: int | None = None) -> Mesh:
    """The 1-D node-axis mesh of the giant/ring paths (parallel/ring.py,
    parallel/giant.py)."""
    grid = device_grid(n_devices)
    return Mesh(grid, (NODE_AXIS,))


def make_hybrid_mesh(
    dcn_size: int | None = None, ici_size: int | None = None
) -> Mesh:
    """A 2-D (dcn, ici) mesh: outer axis across hosts, inner across each
    host's chips.  In a single process the axes are a reshape of the local
    devices (dcn_size defaults to 1); in a multi-process runtime the outer
    axis defaults to the process count so each host owns one DCN row.
    """
    devices = jax.devices()
    n_proc = jax.process_count()
    if dcn_size is None:
        dcn_size = n_proc if n_proc > 1 else 1
    if ici_size is None:
        if len(devices) % dcn_size:
            raise ValueError(
                f"{len(devices)} devices not divisible by dcn axis {dcn_size}"
            )
        ici_size = len(devices) // dcn_size
    if n_proc > 1:
        # Group devices so each DCN row is one process's chips: collectives
        # inside an ici row then ride ICI only.  The requested factorization
        # must match the process layout exactly — a silently truncated or
        # ragged grid would drop devices.
        by_proc: dict[int, list] = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        if len(by_proc) != dcn_size:
            raise ValueError(
                f"dcn axis {dcn_size} != process count {len(by_proc)}; one DCN "
                "row per process is required in multi-process mode"
            )
        rows = []
        for pid, ds in sorted(by_proc.items()):
            if len(ds) != ici_size:
                raise ValueError(
                    f"process {pid} has {len(ds)} devices, ici axis needs {ici_size}"
                )
            rows.append(sorted(ds, key=lambda d: d.id))
        grid = np.asarray(rows)
    else:
        grid = device_grid(dcn_size * ici_size, (dcn_size, ici_size))
    assert grid.shape == (dcn_size, ici_size)
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


# ---------------------------------------------------------------------------
# production sharding policy (NEMO_SHARD_* knobs)
# ---------------------------------------------------------------------------


def _shard_env() -> str:
    """Parse + validate NEMO_SHARD.  Loud on junk, like NEMO_ANALYSIS_IMPL:
    a typo silently resolving to auto would change how many devices execute
    the corpus in exactly the dimension the operator was pinning."""
    v = os.environ.get("NEMO_SHARD", "auto").strip().lower()
    if v in ("auto",):
        return "auto"
    if v in ("1", "true", "yes", "on"):
        return "on"
    if v in ("0", "false", "no", "off"):
        return "off"
    raise ValueError(f"NEMO_SHARD={v!r} (expected auto, 1, or 0)")


def _shard_devices_cap() -> int | None:
    """Parse + validate NEMO_SHARD_DEVICES (None = no cap).  Loud on junk:
    a typo silently lifting the cap would change the mesh width in exactly
    the dimension the operator was pinning (the NEMO_ANALYSIS_IMPL policy;
    NEMO_MAX_BATCH moved to warn-and-default for the serving tier, but
    this knob is read at mesh construction, not per admitted request)."""
    cap = os.environ.get("NEMO_SHARD_DEVICES", "").strip()
    if not cap:
        return None
    try:
        c = int(cap)
    except ValueError:
        raise ValueError(
            f"NEMO_SHARD_DEVICES={cap!r} is not an integer"
        ) from None
    if c < 1:
        raise ValueError(f"NEMO_SHARD_DEVICES={c} must be >= 1")
    return c


def shard_plan() -> tuple[bool, int]:
    """The production sharding decision: (place_on_mesh, n_devices).

    ``place_on_mesh`` False means the single-device dispatch — no mesh, no
    padding, the exact pre-sharding path.  NEMO_SHARD=1 returns True even
    on one device (a no-op placement kept dispatchable so parity suites can
    drive the mesh path without multiple devices); auto places only when
    >1 device is actually visible under the NEMO_SHARD_DEVICES cap."""
    mode = _shard_env()
    if mode == "off":
        return False, 1
    n = len(jax.devices())
    cap = _shard_devices_cap()
    if cap is not None:
        n = min(n, cap)
    if mode == "auto":
        return n > 1, n
    return True, max(1, n)


def shard_device_count() -> int:
    """Number of devices the production run mesh spans under the current
    NEMO_SHARD / NEMO_SHARD_DEVICES settings: 1 means the single-device
    dispatch (no mesh placement at all)."""
    place, n = shard_plan()
    return n if place else 1


#: Process-cached production run mesh, keyed by device count (the visible
#: device set is fixed per process; only the NEMO_SHARD_DEVICES cap varies).
_RUN_MESH_CACHE: dict[int, Mesh] = {}


def run_mesh(n_devices: int) -> Mesh:
    mesh = _RUN_MESH_CACHE.get(n_devices)
    if mesh is None:
        mesh = _RUN_MESH_CACHE[n_devices] = make_run_mesh(n_devices)
    return mesh


def pad_batch_rows(arrays: BatchArrays, multiple: int) -> tuple[BatchArrays, int]:
    """Pad the run axis to a multiple of the mesh size (padding rows are
    empty graphs: node_mask/edge_mask all False).  Returns (padded, n_real)."""
    b = arrays.is_goal.shape[0]
    target = ((b + multiple - 1) // multiple) * multiple
    if target == b:
        return arrays, b
    pad = target - b

    def pad_rows(x):
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return np.pad(np.asarray(x), widths)

    padded = BatchArrays(
        edge_src=pad_rows(arrays.edge_src),
        edge_dst=pad_rows(arrays.edge_dst),
        edge_mask=pad_rows(arrays.edge_mask),
        is_goal=pad_rows(arrays.is_goal),
        table_id=pad_rows(arrays.table_id),
        label_id=pad_rows(arrays.label_id),
        type_id=pad_rows(arrays.type_id),
        node_mask=pad_rows(arrays.node_mask),
    )
    return padded, b


def shard_arrays(mesh: Mesh, arrays: BatchArrays, spec: P | None = None) -> BatchArrays:
    """Place each [B, ...] array with the run axis sharded over the mesh
    (per `spec`; default: the 1-D run axis)."""
    sharding = NamedSharding(mesh, spec if spec is not None else P(RUN_AXIS))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), arrays)


def pad_place_named_arrays(
    arrays: dict, b: int, n_devices: int
) -> tuple[dict, int]:
    """The executor-boundary form of pad_batch_rows + shard_arrays: pad the
    run axis of every [b, ...] array in the fused verb's named-array dict to
    a multiple of the mesh size (padding rows are empty graphs — all masks
    False, indices 0 — exactly pack_batch's own padding rows) and place it
    with ``NamedSharding(run_mesh, P(RUN_AXIS))``; arrays whose leading dim
    is not the run axis (the [1,1] label stubs the narrowing pass leaves
    when the diff tail is off) replicate.  Returns (placed, b_padded).

    One host->device placement per array here, ONE gather per bucket on the
    way back (backend/jax_backend.py materializes outputs post-dispatch) —
    the one-gather rule that keeps shard traffic off the per-verb paths.

    On the production path this NEVER copies host-side: the bucketizer
    folds the shard multiple into its run-axis pad
    (graphs/packed.py:_pad_run_axis, ISSUE 10 satellite / ROADMAP 3b), so
    b is already a mesh multiple and every array goes straight to
    device_put.  A batch that does still need the pad (hand-built batches,
    a mesh wider than the bucketizer planned for) pays one np.pad per
    array and counts ``analysis.shard.pad_copies`` — the regression signal
    tests/test_shard.py watches."""
    from nemo_tpu import obs

    mesh = run_mesh(n_devices)
    row_sharded = NamedSharding(mesh, P(RUN_AXIS))
    replicated = NamedSharding(mesh, P())
    b_pad = ((b + n_devices - 1) // n_devices) * n_devices
    if b_pad != b:
        obs.metrics.inc("analysis.shard.pad_copies")
    out: dict = {}
    for name, a in arrays.items():
        if a is None:
            out[name] = None
            continue
        a = np.asarray(a)
        if a.ndim and a.shape[0] == b:
            if b_pad != b:
                widths = [(0, b_pad - b)] + [(0, 0)] * (a.ndim - 1)
                a = np.pad(a, widths)
            out[name] = jax.device_put(a, row_sharded)
        else:
            out[name] = jax.device_put(a, replicated)
    return out, b_pad


def run_step_sharded(
    mesh: Mesh, spec: P, pre: BatchArrays, post: BatchArrays, static: dict
) -> dict:
    """Pad the run axis to the mesh size, shard it per `spec`, run the
    flagship step, and un-pad the per-run outputs.

    Row 0 (the successful run every failed run diffs against,
    differential-provenance.go:26) is needed by all shards; XLA inserts the
    broadcast of that slice plus the all-reduces for the prototype
    intersection/union automatically from the sharding annotations.

    pack_out (VERDICT r4 task 3): the transfer folding WORKS under
    sharding — jnp.packbits of the concatenated summary ravel makes GSPMD
    all-gather the (tiny, bit-packed) shards into one replicated vector,
    so the host still pays ONE device->host copy per step instead of one
    per output array; the run-axis un-pad happens host-side after the
    unpack (the padded batch size is the unpack's b), which is why the
    old in-jit layout couldn't be row-sliced directly.  The static dict's
    pack_out flag is honored; only closure_impl is overridden (GSPMD
    cannot shard through a Mosaic pallas_call)."""
    pre_s, n_real = pad_batch_rows(pre, mesh.devices.size)
    post_s, _ = pad_batch_rows(post, mesh.devices.size)
    b_pad = pre_s.is_goal.shape[0]
    pre_s = shard_arrays(mesh, pre_s, spec)
    post_s = shard_arrays(mesh, post_s, spec)
    pack_out = bool(static.get("pack_out", False))
    out = analysis_step(pre_s, post_s, **{**static, "closure_impl": "xla"})
    if pack_out and "packed_summary" in out:
        from nemo_tpu.backend.jax_backend import _unpack_summary

        out = dict(out)
        out.update(
            _unpack_summary(
                out.pop("packed_summary"),
                b=b_pad,
                v=int(static["v"]),
                t=int(static["num_tables"]),
                with_diff=bool(static.get("with_diff", True)),
            )
        )
    # Un-pad only the outputs whose leading axis is the run axis; corpus-level
    # outputs (proto_inter/proto_union over the table axis) pass through.
    corpus_level = {"proto_inter", "proto_union"}
    return {k: v if k in corpus_level else v[:n_real] for k, v in out.items()}


def analysis_step_sharded(
    mesh: Mesh, pre: BatchArrays, post: BatchArrays, static: dict
) -> dict:
    """The flagship step with the run batch data-parallel over a 1-D mesh."""
    return run_step_sharded(mesh, P(RUN_AXIS), pre, post, static)
