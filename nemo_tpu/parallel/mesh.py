"""Device-mesh scaling of the analysis pipeline.

The run axis is the framework's data-parallel axis (SURVEY.md §2.3): the
reference analyzes runs in a sequential host loop; here the packed run batch
is sharded over a 1-D `jax.sharding.Mesh` and the same jitted analysis_step
runs SPMD, with the cross-run prototype reductions (jnp.all/any over the run
axis) lowered by XLA to all-reduces over ICI.  Multi-host scale-out uses the
same code path — jax.distributed + a larger mesh — with DCN only between
hosts, never inside the per-run kernels.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nemo_tpu.models.pipeline_model import BatchArrays, analysis_step

RUN_AXIS = "run"
NODE_AXIS = "node"


def make_run_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.asarray(devices[:n]).reshape(n), (RUN_AXIS,))


def pad_batch_rows(arrays: BatchArrays, multiple: int) -> tuple[BatchArrays, int]:
    """Pad the run axis to a multiple of the mesh size (padding rows are
    empty graphs: node_mask/edge_mask all False).  Returns (padded, n_real)."""
    b = arrays.is_goal.shape[0]
    target = ((b + multiple - 1) // multiple) * multiple
    if target == b:
        return arrays, b
    pad = target - b

    def pad_rows(x):
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return np.pad(np.asarray(x), widths)

    padded = BatchArrays(
        edge_src=pad_rows(arrays.edge_src),
        edge_dst=pad_rows(arrays.edge_dst),
        edge_mask=pad_rows(arrays.edge_mask),
        is_goal=pad_rows(arrays.is_goal),
        table_id=pad_rows(arrays.table_id),
        label_id=pad_rows(arrays.label_id),
        type_id=pad_rows(arrays.type_id),
        node_mask=pad_rows(arrays.node_mask),
    )
    return padded, b


def shard_arrays(mesh: Mesh, arrays: BatchArrays, spec: P | None = None) -> BatchArrays:
    """Place each [B, ...] array with the run axis sharded over the mesh
    (per `spec`; default: the 1-D run axis)."""
    sharding = NamedSharding(mesh, spec if spec is not None else P(RUN_AXIS))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), arrays)


def run_step_sharded(
    mesh: Mesh, spec: P, pre: BatchArrays, post: BatchArrays, static: dict
) -> dict:
    """Pad the run axis to the mesh size, shard it per `spec`, run the
    flagship step, and un-pad the per-run outputs.

    Row 0 (the successful run every failed run diffs against,
    differential-provenance.go:26) is needed by all shards; XLA inserts the
    broadcast of that slice plus the all-reduces for the prototype
    intersection/union automatically from the sharding annotations.

    pack_out (VERDICT r4 task 3): the transfer folding WORKS under
    sharding — jnp.packbits of the concatenated summary ravel makes GSPMD
    all-gather the (tiny, bit-packed) shards into one replicated vector,
    so the host still pays ONE device->host copy per step instead of one
    per output array; the run-axis un-pad happens host-side after the
    unpack (the padded batch size is the unpack's b), which is why the
    old in-jit layout couldn't be row-sliced directly.  The static dict's
    pack_out flag is honored; only closure_impl is overridden (GSPMD
    cannot shard through a Mosaic pallas_call)."""
    pre_s, n_real = pad_batch_rows(pre, mesh.devices.size)
    post_s, _ = pad_batch_rows(post, mesh.devices.size)
    b_pad = pre_s.is_goal.shape[0]
    pre_s = shard_arrays(mesh, pre_s, spec)
    post_s = shard_arrays(mesh, post_s, spec)
    pack_out = bool(static.get("pack_out", False))
    out = analysis_step(pre_s, post_s, **{**static, "closure_impl": "xla"})
    if pack_out and "packed_summary" in out:
        from nemo_tpu.backend.jax_backend import _unpack_summary

        out = dict(out)
        out.update(
            _unpack_summary(
                out.pop("packed_summary"),
                b=b_pad,
                v=int(static["v"]),
                t=int(static["num_tables"]),
                with_diff=bool(static.get("with_diff", True)),
            )
        )
    # Un-pad only the outputs whose leading axis is the run axis; corpus-level
    # outputs (proto_inter/proto_union over the table axis) pass through.
    corpus_level = {"proto_inter", "proto_union"}
    return {k: v if k in corpus_level else v[:n_real] for k, v in out.items()}


def analysis_step_sharded(
    mesh: Mesh, pre: BatchArrays, post: BatchArrays, static: dict
) -> dict:
    """The flagship step with the run batch data-parallel over a 1-D mesh."""
    return run_step_sharded(mesh, P(RUN_AXIS), pre, post, static)
