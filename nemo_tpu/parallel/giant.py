"""Giant-graph analysis: the fused per-run pipeline for a provenance graph
too large for the batched dense buckets.

The batched path (models/pipeline_model.py) holds [B,V,V] adjacencies and
runs all-pairs closures — the right trade at case-study sizes (V <= a few
hundred), but a single giant run (deep @next chains, SURVEY.md §5's
long-context analog) would OOM the bucket and waste V^3·log V closure work
on a shallow DAG.  This path analyzes ONE run with:

  * the node dimension sharded over a 1-D device mesh (column-sharded
    adjacency, XLA/GSPMD inserts the ICI collectives — same layout as
    parallel/ring.py's explicit ring schedule);
  * closure-free kernels: component labels by O(V log V) pointer doubling
    (verified-linear chains) or exact host union-find labels shipped in
    (any other member structure — no bounded DEVICE iteration is sound
    there, see giant_plan), and prototype reachability by set-BFS,
    O(proto_depth · V^2) (ops/proto.py:proto_rule_bits use_closure=False)
    — exact because the DIRECTED depth bound holds for directed BFS.

The JaxBackend auto-dispatches a run past NEMO_GIANT_V out of the dense
buckets (backend/jax_backend.py), so one oversized run in an otherwise
normal corpus analyzes correctly end-to-end; outputs are row-compatible
with the fused step's (B=1).  Routing order (ISSUE 10,
backend/jax_backend.py:_giant_impl_default): on a REAL device the default
giant route is now the sparse-CSR DEVICE step (ops/sparse_device.py —
O(V+E) memory, no node-sharded dense closures); this module's dense
node-sharded step remains the NEMO_GIANT_IMPL=device opt-in, and
giant_analysis_host below is the CPU-platform resolution and the
breaker/failover degraded mode — no longer the only giant escape hatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nemo_tpu.ops.adjacency import build_adjacency
from nemo_tpu.ops.condition import mark_condition_holds
from nemo_tpu.ops.proto import all_rule_bits, proto_rule_bits
from nemo_tpu.ops.simplify import clean_masks, collapse_chains

from .mesh import NODE_AXIS
from .ring import make_node_mesh


def giant_plan(graph) -> tuple[bool, int, "object"]:
    """Host-side O(E) planning for one giant run (graphs.packed.PackedGraph):
    returns (chains_linear, collapsed_depth_bound, comp_labels).

    chains_linear: every @next chain member has at most one member
    successor/predecessor in the CLEAN graph — true for the linear
    `t(C+1)@next :- t(C)` chains the domain generates, enabling the
    O(V log V) pointer-doubling labels on device.

    comp_labels [n_nodes] int32: EXACT union-find component labels of the
    member subgraph (member-index-valued; the sentinel for non-members is
    n_nodes — pad_comp_labels re-sentinels to the bucket V when padding).  The giant
    step uses these when the chains are NOT linear: no bounded device
    iteration is sound there — an undirected member component's diameter is
    not bounded by the directed longest path (alternating-orientation
    "zigzag" structures grow the diameter with component size while the
    directed depth stays constant), so only precomputed exact labels keep
    the contraction equal to the oracle's component semantics.

    collapsed_depth_bound: longest path of the graph AFTER contracting each
    chain component to one node (+1 margin) — the tight trip count for the
    post-simplification BFS kernels, small even when raw chains are
    thousands of timesteps deep."""
    import numpy as np

    from nemo_tpu.graphs.packed import TYPE_NEXT, longest_path_len

    n = graph.n_nodes
    ng = graph.n_goals
    edges = graph.edges
    is_goal = np.zeros(n, dtype=bool)
    is_goal[:ng] = True
    # clean_masks mirror: rules alive iff they have both an in-goal and an
    # out-goal edge; edge g->r kept iff r has an out-goal, r->g iff r has an
    # in-goal (ops/simplify.py:clean_masks).
    has_in_goal = np.zeros(n, dtype=bool)
    has_out_goal = np.zeros(n, dtype=bool)
    if len(edges):
        src, dst = edges[:, 0], edges[:, 1]
        np.logical_or.at(has_in_goal, dst, is_goal[src])
        np.logical_or.at(has_out_goal, src, is_goal[dst])
    rule_alive = ~is_goal & has_in_goal & has_out_goal
    alive = is_goal | rule_alive
    if len(edges):
        keep = np.where(is_goal[src], has_out_goal[dst], has_in_goal[src])
        keep &= alive[src] & alive[dst]
        src, dst = src[keep], dst[keep]
    else:
        src = dst = np.zeros(0, dtype=np.int64)

    next_rule = ~is_goal & alive & (graph.type_id == TYPE_NEXT)
    in_from_next = np.zeros(n, dtype=bool)
    out_to_next = np.zeros(n, dtype=bool)
    if len(src):
        np.logical_or.at(in_from_next, dst, next_rule[src])
        np.logical_or.at(out_to_next, src, next_rule[dst])
    member = next_rule | (is_goal & alive & in_from_next & out_to_next)

    member_edge = member[src] & member[dst] if len(src) else np.zeros(0, dtype=bool)
    succ_count = np.zeros(n, dtype=np.int64)
    pred_count = np.zeros(n, dtype=np.int64)
    np.add.at(succ_count, src[member_edge], 1)
    np.add.at(pred_count, dst[member_edge], 1)
    linear = bool((succ_count[member] <= 1).all() and (pred_count[member] <= 1).all())

    # Contract chain components (union-find over member edges) and bound the
    # collapsed graph's longest path.
    parent = np.arange(n)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for s, d in zip(src[member_edge], dst[member_edge]):
        rs, rd = find(int(s)), find(int(d))
        if rs != rd:
            parent[rs] = rd
    rep = np.array([find(i) for i in range(n)])
    comp_labels = np.where(member, rep, n).astype(np.int32)
    cedges = np.stack([rep[src], rep[dst]], axis=1) if len(src) else np.zeros((0, 2), int)
    cedges = cedges[cedges[:, 0] != cedges[:, 1]]
    depth = longest_path_len(n, cedges)
    return linear, min(n, depth + 2), comp_labels


def pad_comp_labels(labels, n_nodes: int, v: int):
    """giant_plan's [n_nodes] labels -> the giant verb's [1, v] plane, with
    the non-member sentinel re-pinned to the bucket V (collapse_chains masks
    by member, so any >= n value works; V keeps it shape-consistent)."""
    import numpy as np

    out = np.full((1, v), v, dtype=np.int32)
    out[0, :n_nodes] = labels
    return out


_MESH_CACHE: dict[int, Mesh] = {}


def default_node_mesh(v: int) -> Mesh:
    """Largest power-of-two device count that divides v (v is a power-of-two
    bucket, so any power of two <= min(v, n_devices) works).  Cached per
    size so repeat calls share one Mesh (and the jit cache below hits)."""
    n_dev = len(jax.devices())
    n = 1
    while n * 2 <= n_dev and v % (n * 2) == 0:
        n *= 2
    mesh = _MESH_CACHE.get(n)
    if mesh is None:
        mesh = _MESH_CACHE[n] = make_node_mesh(n)
    return mesh


_JIT_CACHE: dict = {}


def giant_analysis_step(
    pre,
    post,
    v: int,
    pre_tid: int,
    post_tid: int,
    num_tables: int,
    max_depth: int,
    comp_linear: bool = True,
    proto_depth: int | None = None,
    mesh: Mesh | None = None,
    pre_labels=None,
    post_labels=None,
    pack_out: bool = False,
) -> dict[str, jnp.ndarray]:
    """Fused-step-compatible outputs for ONE giant run (B=1 batches).

    pack_out=True folds the bool summary outputs into one bit-packed
    "packed_summary" vector (models/pipeline_model.py:GIANT_PACK_LAYOUT)
    inside the compiled program — same transfer-folding rationale as the
    dense fused step (the device tunnel serializes each device->host copy
    at ~an RTT); backend/jax_backend.py:_unpack_summary inverts it.

    pre/post: models.pipeline_model.BatchArrays with leading dim 1.
    comp_linear/proto_depth/labels come from giant_plan (host-side O(E));
    max_depth is the RAW longest-path bound, proto_depth the collapsed
    one (the BFS kernels run post-simplification, so the collapsed bound
    keeps trip counts small even under thousand-step chains).

    comp_linear=True uses O(V log V) pointer-doubling labels on device
    (exact for the verified-linear chains).  comp_linear=False expects
    pre_labels/post_labels [1,V] — giant_plan's exact union-find labels —
    because no bounded device iteration is sound for arbitrary member
    structures (an undirected component's diameter is not bounded by the
    directed longest path); without them (a one-version-behind Kernel RPC
    client) the step falls back to the exact all-pairs closure labeling.
    Returns the same keys as analysis_step(with_diff=False)."""
    mesh = mesh or default_node_mesh(v)
    n_dev = mesh.devices.size
    if v % n_dev:
        raise ValueError(f"V={v} not divisible by node mesh size {n_dev}")
    spec_node = NamedSharding(mesh, P(None, NODE_AXIS))
    spec_adj = NamedSharding(mesh, P(None, None, NODE_AXIS))
    proto_depth = proto_depth or max_depth

    key = (
        tuple(d.id for d in mesh.devices.flat),  # mesh identity, not just size
        v,
        int(pre.edge_src.shape[-1]),
        int(post.edge_src.shape[-1]),
        num_tables,
        # max_depth deliberately NOT in the key: the trace no longer uses it
        # (the bounded-propagation path is gone), and distinct depth buckets
        # would recompile identical programs at tens of seconds each.
        comp_linear,
        proto_depth,
        pack_out,
    )
    # Label strategy, in order of preference:
    #   doubling  verified-linear chains, O(V log V) on device
    #   host      exact union-find labels shipped in (the only sound bounded
    #             option for arbitrary member structures)
    #   closure   no labels supplied (e.g. a one-version-behind client over
    #             the Kernel RPC): the assumption-free all-pairs closure —
    #             O(V^3 log V) at giant V is expensive but CORRECT, which
    #             beats the pre-r4 bounded propagation that silently
    #             under-labeled zigzag components.
    label_mode = (
        "doubling"
        if comp_linear
        else ("host" if pre_labels is not None and post_labels is not None else "closure")
    )
    key = key + (label_mode,)
    fn = _JIT_CACHE.get(key)
    if fn is None:

        @jax.jit
        def fn(pre, post, pre_tid, post_tid, pre_lab, post_lab):
            out = {}
            alive2 = {}
            labs = {"pre": pre_lab, "post": post_lab}
            for name, b, tid in (("pre", pre, pre_tid), ("post", post, post_tid)):
                adj = build_adjacency(b.edge_src, b.edge_dst, b.edge_mask, v)
                adj = lax.with_sharding_constraint(adj, spec_adj)
                out[f"{name}_holds"] = mark_condition_holds(
                    adj, b.is_goal, b.table_id, b.node_mask, tid, num_tables
                )
                adj_c, alive = clean_masks(adj, b.is_goal, b.node_mask)
                # Edge rewiring always by O(V^2) scatters — no V^3 matmul.
                adj2, alive2[name], type2 = collapse_chains(
                    adj_c,
                    b.is_goal,
                    b.type_id,
                    alive,
                    comp_doubling=label_mode == "doubling",
                    comp_labels=labs[name] if label_mode == "host" else None,
                    rewire="scatter",
                )
                out[f"{name}_adj_clean"] = lax.with_sharding_constraint(adj2, spec_adj)
                out[f"{name}_alive"] = alive2[name]
                out[f"{name}_type"] = type2
            achieved = out["pre_holds"].any(axis=-1)
            out["achieved_pre"] = achieved
            bits, min_depth = proto_rule_bits(
                out["post_adj_clean"],
                post.is_goal,
                alive2["post"],
                post.table_id,
                achieved,
                num_tables,
                proto_depth,
                use_closure=False,
            )
            out["proto_bits"] = bits
            out["proto_min_depth"] = min_depth
            out["proto_present"] = all_rule_bits(
                post.is_goal, alive2["post"], post.table_id, num_tables
            )
            if pack_out:
                from nemo_tpu.models.pipeline_model import (
                    GIANT_PACK_LAYOUT,
                    fold_packed_summary,
                )

                fold_packed_summary(out, GIANT_PACK_LAYOUT)
            return out

        _JIT_CACHE[key] = fn

    def shard(b):
        import dataclasses

        return dataclasses.replace(
            b,
            is_goal=jax.device_put(b.is_goal, spec_node),
            table_id=jax.device_put(b.table_id, spec_node),
            label_id=jax.device_put(b.label_id, spec_node),
            type_id=jax.device_put(b.type_id, spec_node),
            node_mask=jax.device_put(b.node_mask, spec_node),
        )

    if pre_labels is None:
        # Unused by the non-"host" traces; a zero plane keeps the jit
        # signature uniform across the variants.
        pre_labels = jnp.zeros(pre.is_goal.shape, dtype=jnp.int32)
    if post_labels is None:
        post_labels = jnp.zeros(post.is_goal.shape, dtype=jnp.int32)
    # jnp.asarray + device_put: no host round-trip when the planes already
    # live on device (the executor converts kernel inputs eagerly; a numpy
    # coercion here would cost two synchronous tunnel transfers per run).
    return fn(
        shard(pre),
        shard(post),
        pre_tid,
        post_tid,
        jax.device_put(jnp.asarray(pre_labels, dtype=jnp.int32), spec_node),
        jax.device_put(jnp.asarray(post_labels, dtype=jnp.int32), spec_node),
    )


def giant_analysis_host(
    pre,
    post,
    pre_tid: int,
    post_tid: int,
    num_tables: int,
    pre_labels,
    post_labels,
) -> dict:
    """Exact sparse HOST mirror of giant_analysis_step (VERDICT r4 task 2).

    Same inputs (B=1 PackedBatch pair + giant_plan's padded union-find
    label planes), same output keys/shapes/dtypes — but every kernel runs
    as O(V + E) numpy edge-list scatters and fix-point BFS instead of
    dense [V,V] device work.  This is the CPU-platform resolution of the
    giant crossover (backend/jax_backend.py:_giant_impl_default): on a CPU
    fallback the dense node-sharded path is 5-6x SLOWER than the
    sequential oracle (BENCH_r04: 87.4 s vs 14.3 s for the 10k-node run),
    while this path does the same analysis in milliseconds.  On a REAL
    device it is NO LONGER the only giant escape hatch (ISSUE 10): the
    default there is the sparse-CSR DEVICE step (ops/sparse_device.py via
    the sparse_fused verb — giant runs stay on the accelerator in O(V+E)
    memory), and this host path serves the NEMO_GIANT_IMPL=host pin, the
    breaker/failover degraded mode, and tunnel-less deployments.

    Exactness notes (vs the bounded device kernels):
      * BFS sweeps run to fix point, so no depth bound is needed;
      * component labels are giant_plan's exact union-find labels — the
        same partition the device uses in "host"-label mode, and the same
        reps (min head index per component) in "doubling" mode;
      * the dense [V,V] adj_clean planes are materialized host-side only
        here (downstream row-gathers and figure rendering index them the
        same way they index the device gathers).

    Reference semantics: markConditionHolds (pre-post-prov.go:220-243),
    clean-copy + collapseNextChains (preprocessing.go:17-345),
    extractProtos (prototype.go:11-24) — via the array forms in
    ops/condition.py, ops/simplify.py, ops/proto.py.
    """
    import numpy as np

    from nemo_tpu.graphs.packed import TYPE_COLLAPSED, TYPE_NEXT
    from nemo_tpu.ops.proto import DEPTH_INF

    out: dict = {}
    alive_clean: dict = {}
    coll_edges: dict = {}
    labs = {"pre": pre_labels, "post": post_labels}

    for name, b, tid in (("pre", pre, pre_tid), ("post", post, post_tid)):
        v = b.v
        idx = np.arange(v)
        is_goal = np.asarray(b.is_goal[0])
        node_mask = np.asarray(b.node_mask[0])
        table = np.asarray(b.table_id[0]).astype(np.int64)
        type_id = np.asarray(b.type_id[0]).astype(np.int32)
        em = np.asarray(b.edge_mask[0]).astype(bool)
        src = np.asarray(b.edge_src[0])[em].astype(np.int64)
        dst = np.asarray(b.edge_dst[0])[em].astype(np.int64)

        def scat_any(at, vals, v=v):
            """bool [v]: any val scattered to index (bincount beats
            ufunc.at by orders of magnitude at giant E)."""
            return np.bincount(at[vals], minlength=v) > 0

        goal = is_goal & node_mask

        # --- condition marking (ops/condition.py:mark_condition_holds)
        indeg = scat_any(dst, np.ones(len(dst), dtype=bool))
        root = goal & (table == tid) & ~indeg
        rule = scat_any(dst, root[src]) & ~is_goal & node_mask & (table == tid)
        trig = scat_any(dst, rule[src]) & is_goal & node_mask
        any_trig = bool(trig.any())
        trig_tables = np.zeros(num_tables, dtype=bool)
        tt = table[trig]
        trig_tables[np.clip(tt, 0, num_tables - 1)[tt >= 0]] = True
        in_trig_table = trig_tables[np.clip(table, 0, num_tables - 1)] & (table >= 0)
        holds = goal & any_trig & ((table == tid) | in_trig_table)
        out[f"{name}_holds"] = holds[None]

        # --- clean-copy restriction (ops/simplify.py:clean_masks)
        has_in_goal = scat_any(dst, goal[src])
        has_out_goal = scat_any(src, goal[dst])
        is_rule = ~is_goal & node_mask
        alive = goal | (is_rule & has_in_goal & has_out_goal)
        keep = np.where(goal[src], has_out_goal[dst], has_in_goal[src])
        keep &= alive[src] & alive[dst]
        ksrc, kdst = src[keep], dst[keep]

        # --- chain contraction (ops/simplify.py:collapse_chains)
        next_rule = is_rule & alive & (type_id == TYPE_NEXT)
        in_from_next = scat_any(kdst, next_rule[ksrc])
        out_to_next = scat_any(ksrc, next_rule[kdst])
        member = next_rule | (goal & alive & in_from_next & out_to_next)
        lab = np.where(member, np.asarray(labs[name][0]).astype(np.int64), v)
        in_from_member = scat_any(kdst, member[ksrc])
        out_to_member = scat_any(ksrc, member[kdst])
        head = next_rule & ~in_from_member
        tail = next_rule & ~out_to_member

        rep_per_comp = np.full(v, v, dtype=np.int64)
        hm = member & head
        np.minimum.at(rep_per_comp, np.clip(lab[hm], 0, v - 1), idx[hm])
        nm = member & next_rule
        n_rules_per_comp = np.bincount(np.clip(lab[nm], 0, v - 1), minlength=v)
        collapsible_comp = (n_rules_per_comp >= 2) & (rep_per_comp < v)
        lab_c = np.clip(lab, 0, v - 1)
        node_collapsible = member & collapsible_comp[lab_c]
        rep_of_node = np.where(node_collapsible, rep_per_comp[lab_c], idx)
        is_rep = node_collapsible & (idx == rep_of_node)
        dies = node_collapsible & ~is_rep
        ext_goal = goal & alive & ~member

        survive = ~node_collapsible[ksrc] & ~node_collapsible[kdst]
        pred_sel = ext_goal[ksrc] & (head & node_collapsible)[kdst]
        succ_sel = (tail & node_collapsible)[ksrc] & ext_goal[kdst]
        new_src = np.concatenate(
            [ksrc[survive], ksrc[pred_sel], rep_of_node[ksrc[succ_sel]]]
        )
        new_dst = np.concatenate(
            [kdst[survive], rep_of_node[kdst[pred_sel]], kdst[succ_sel]]
        )
        alive_new = alive & ~dies
        type_new = np.where(is_rep, TYPE_COLLAPSED, type_id).astype(type_id.dtype)
        adj_new = np.zeros((v, v), dtype=bool)
        adj_new[new_src, new_dst] = True
        out[f"{name}_adj_clean"] = adj_new[None]
        out[f"{name}_alive"] = alive_new[None]
        out[f"{name}_type"] = type_new[None]
        alive_clean[name] = alive_new
        coll_edges[name] = (new_src, new_dst, is_goal, table)

    achieved = bool(out["pre_holds"].any())
    out["achieved_pre"] = np.array([achieved])

    # --- prototype bits on the collapsed consequent (ops/proto.py)
    v = post.v
    asrc, adst, is_goal_p, table_p = coll_edges["post"]
    alive2 = alive_clean["post"]
    ok = alive2[asrc] & alive2[adst]
    asrc, adst = asrc[ok], adst[ok]

    def scat_any_p(at, vals):
        return np.bincount(at[vals], minlength=v) > 0

    def bfs_any(start, forward: bool) -> "np.ndarray":
        """Nodes reachable from `start` in >= 1 hop; exact fix point."""
        s, d = (asrc, adst) if forward else (adst, asrc)
        reach = np.zeros(v, dtype=bool)
        frontier = start
        while True:
            nxt = scat_any_p(d, frontier[s]) & ~reach
            if not nxt.any():
                return reach
            reach |= nxt
            frontier = nxt

    indeg2 = scat_any_p(adst, np.ones(len(adst), dtype=bool))
    root2 = is_goal_p & alive2 & ~indeg2
    is_rule2 = ~is_goal_p & alive2
    reach = bfs_any(root2, forward=True)
    rule_desc = bfs_any(is_rule2, forward=False)
    rule_anc = bfs_any(is_rule2 & reach, forward=True)
    qualify = is_rule2 & reach & (rule_desc | rule_anc) & achieved

    depth = np.full(v, DEPTH_INF, dtype=np.int64)
    depth[root2] = 0
    frontier, d = root2, 0
    while frontier.any():
        d += 1
        nxt = scat_any_p(adst, frontier[asrc]) & (depth == DEPTH_INF)
        depth[nxt] = d
        frontier = nxt
    rule_depth = (depth + 1) // 2

    bits = np.zeros(num_tables, dtype=bool)
    min_depth = np.full(num_tables, DEPTH_INF, dtype=np.int64)
    qt = np.clip(table_p[qualify], 0, num_tables - 1)
    qok = table_p[qualify] >= 0
    bits[qt[qok]] = True
    np.minimum.at(min_depth, qt[qok], rule_depth[qualify][qok])
    present = np.zeros(num_tables, dtype=bool)
    pm = is_rule2 & (table_p >= 0)
    present[np.clip(table_p[pm], 0, num_tables - 1)] = True

    out["proto_bits"] = bits[None]
    out["proto_min_depth"] = min_depth.astype(np.int32)[None]
    out["proto_present"] = present[None]
    return out
