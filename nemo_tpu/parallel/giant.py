"""Giant-graph analysis: the fused per-run pipeline for a provenance graph
too large for the batched dense buckets.

The batched path (models/pipeline_model.py) holds [B,V,V] adjacencies and
runs all-pairs closures — the right trade at case-study sizes (V <= a few
hundred), but a single giant run (deep @next chains, SURVEY.md §5's
long-context analog) would OOM the bucket and waste V^3·log V closure work
on a shallow DAG.  This path analyzes ONE run with:

  * the node dimension sharded over a 1-D device mesh (column-sharded
    adjacency, XLA/GSPMD inserts the ICI collectives — same layout as
    parallel/ring.py's explicit ring schedule);
  * closure-free kernels: component labels by O(V log V) pointer doubling
    (verified-linear chains) or exact host union-find labels shipped in
    (any other member structure — no bounded DEVICE iteration is sound
    there, see giant_plan), and prototype reachability by set-BFS,
    O(proto_depth · V^2) (ops/proto.py:proto_rule_bits use_closure=False)
    — exact because the DIRECTED depth bound holds for directed BFS.

The JaxBackend auto-dispatches here when a run's node count exceeds
NEMO_GIANT_V (backend/jax_backend.py), so one oversized run in an
otherwise normal corpus analyzes correctly end-to-end; outputs are
row-compatible with the fused step's (B=1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nemo_tpu.ops.adjacency import build_adjacency
from nemo_tpu.ops.condition import mark_condition_holds
from nemo_tpu.ops.proto import all_rule_bits, proto_rule_bits
from nemo_tpu.ops.simplify import clean_masks, collapse_chains

from .mesh import NODE_AXIS
from .ring import make_node_mesh


def giant_plan(graph) -> tuple[bool, int, "object"]:
    """Host-side O(E) planning for one giant run (graphs.packed.PackedGraph):
    returns (chains_linear, collapsed_depth_bound, comp_labels).

    chains_linear: every @next chain member has at most one member
    successor/predecessor in the CLEAN graph — true for the linear
    `t(C+1)@next :- t(C)` chains the domain generates, enabling the
    O(V log V) pointer-doubling labels on device.

    comp_labels [n_nodes] int32: EXACT union-find component labels of the
    member subgraph (member-index-valued; the sentinel for non-members is
    n_nodes — pad_comp_labels re-sentinels to the bucket V when padding).  The giant
    step uses these when the chains are NOT linear: no bounded device
    iteration is sound there — an undirected member component's diameter is
    not bounded by the directed longest path (alternating-orientation
    "zigzag" structures grow the diameter with component size while the
    directed depth stays constant), so only precomputed exact labels keep
    the contraction equal to the oracle's component semantics.

    collapsed_depth_bound: longest path of the graph AFTER contracting each
    chain component to one node (+1 margin) — the tight trip count for the
    post-simplification BFS kernels, small even when raw chains are
    thousands of timesteps deep."""
    import numpy as np

    from nemo_tpu.graphs.packed import TYPE_NEXT, longest_path_len

    n = graph.n_nodes
    ng = graph.n_goals
    edges = graph.edges
    is_goal = np.zeros(n, dtype=bool)
    is_goal[:ng] = True
    # clean_masks mirror: rules alive iff they have both an in-goal and an
    # out-goal edge; edge g->r kept iff r has an out-goal, r->g iff r has an
    # in-goal (ops/simplify.py:clean_masks).
    has_in_goal = np.zeros(n, dtype=bool)
    has_out_goal = np.zeros(n, dtype=bool)
    if len(edges):
        src, dst = edges[:, 0], edges[:, 1]
        np.logical_or.at(has_in_goal, dst, is_goal[src])
        np.logical_or.at(has_out_goal, src, is_goal[dst])
    rule_alive = ~is_goal & has_in_goal & has_out_goal
    alive = is_goal | rule_alive
    if len(edges):
        keep = np.where(is_goal[src], has_out_goal[dst], has_in_goal[src])
        keep &= alive[src] & alive[dst]
        src, dst = src[keep], dst[keep]
    else:
        src = dst = np.zeros(0, dtype=np.int64)

    next_rule = ~is_goal & alive & (graph.type_id == TYPE_NEXT)
    in_from_next = np.zeros(n, dtype=bool)
    out_to_next = np.zeros(n, dtype=bool)
    if len(src):
        np.logical_or.at(in_from_next, dst, next_rule[src])
        np.logical_or.at(out_to_next, src, next_rule[dst])
    member = next_rule | (is_goal & alive & in_from_next & out_to_next)

    member_edge = member[src] & member[dst] if len(src) else np.zeros(0, dtype=bool)
    succ_count = np.zeros(n, dtype=np.int64)
    pred_count = np.zeros(n, dtype=np.int64)
    np.add.at(succ_count, src[member_edge], 1)
    np.add.at(pred_count, dst[member_edge], 1)
    linear = bool((succ_count[member] <= 1).all() and (pred_count[member] <= 1).all())

    # Contract chain components (union-find over member edges) and bound the
    # collapsed graph's longest path.
    parent = np.arange(n)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for s, d in zip(src[member_edge], dst[member_edge]):
        rs, rd = find(int(s)), find(int(d))
        if rs != rd:
            parent[rs] = rd
    rep = np.array([find(i) for i in range(n)])
    comp_labels = np.where(member, rep, n).astype(np.int32)
    cedges = np.stack([rep[src], rep[dst]], axis=1) if len(src) else np.zeros((0, 2), int)
    cedges = cedges[cedges[:, 0] != cedges[:, 1]]
    depth = longest_path_len(n, cedges)
    return linear, min(n, depth + 2), comp_labels


def pad_comp_labels(labels, n_nodes: int, v: int):
    """giant_plan's [n_nodes] labels -> the giant verb's [1, v] plane, with
    the non-member sentinel re-pinned to the bucket V (collapse_chains masks
    by member, so any >= n value works; V keeps it shape-consistent)."""
    import numpy as np

    out = np.full((1, v), v, dtype=np.int32)
    out[0, :n_nodes] = labels
    return out


_MESH_CACHE: dict[int, Mesh] = {}


def default_node_mesh(v: int) -> Mesh:
    """Largest power-of-two device count that divides v (v is a power-of-two
    bucket, so any power of two <= min(v, n_devices) works).  Cached per
    size so repeat calls share one Mesh (and the jit cache below hits)."""
    n_dev = len(jax.devices())
    n = 1
    while n * 2 <= n_dev and v % (n * 2) == 0:
        n *= 2
    mesh = _MESH_CACHE.get(n)
    if mesh is None:
        mesh = _MESH_CACHE[n] = make_node_mesh(n)
    return mesh


_JIT_CACHE: dict = {}


def giant_analysis_step(
    pre,
    post,
    v: int,
    pre_tid: int,
    post_tid: int,
    num_tables: int,
    max_depth: int,
    comp_linear: bool = True,
    proto_depth: int | None = None,
    mesh: Mesh | None = None,
    pre_labels=None,
    post_labels=None,
    pack_out: bool = False,
) -> dict[str, jnp.ndarray]:
    """Fused-step-compatible outputs for ONE giant run (B=1 batches).

    pack_out=True folds the bool summary outputs into one bit-packed
    "packed_summary" vector (models/pipeline_model.py:GIANT_PACK_LAYOUT)
    inside the compiled program — same transfer-folding rationale as the
    dense fused step (the device tunnel serializes each device->host copy
    at ~an RTT); backend/jax_backend.py:_unpack_summary inverts it.

    pre/post: models.pipeline_model.BatchArrays with leading dim 1.
    comp_linear/proto_depth/labels come from giant_plan (host-side O(E));
    max_depth is the RAW longest-path bound, proto_depth the collapsed
    one (the BFS kernels run post-simplification, so the collapsed bound
    keeps trip counts small even under thousand-step chains).

    comp_linear=True uses O(V log V) pointer-doubling labels on device
    (exact for the verified-linear chains).  comp_linear=False expects
    pre_labels/post_labels [1,V] — giant_plan's exact union-find labels —
    because no bounded device iteration is sound for arbitrary member
    structures (an undirected component's diameter is not bounded by the
    directed longest path); without them (a one-version-behind Kernel RPC
    client) the step falls back to the exact all-pairs closure labeling.
    Returns the same keys as analysis_step(with_diff=False)."""
    mesh = mesh or default_node_mesh(v)
    n_dev = mesh.devices.size
    if v % n_dev:
        raise ValueError(f"V={v} not divisible by node mesh size {n_dev}")
    spec_node = NamedSharding(mesh, P(None, NODE_AXIS))
    spec_adj = NamedSharding(mesh, P(None, None, NODE_AXIS))
    proto_depth = proto_depth or max_depth

    key = (
        tuple(d.id for d in mesh.devices.flat),  # mesh identity, not just size
        v,
        int(pre.edge_src.shape[-1]),
        int(post.edge_src.shape[-1]),
        num_tables,
        # max_depth deliberately NOT in the key: the trace no longer uses it
        # (the bounded-propagation path is gone), and distinct depth buckets
        # would recompile identical programs at tens of seconds each.
        comp_linear,
        proto_depth,
        pack_out,
    )
    # Label strategy, in order of preference:
    #   doubling  verified-linear chains, O(V log V) on device
    #   host      exact union-find labels shipped in (the only sound bounded
    #             option for arbitrary member structures)
    #   closure   no labels supplied (e.g. a one-version-behind client over
    #             the Kernel RPC): the assumption-free all-pairs closure —
    #             O(V^3 log V) at giant V is expensive but CORRECT, which
    #             beats the pre-r4 bounded propagation that silently
    #             under-labeled zigzag components.
    label_mode = (
        "doubling"
        if comp_linear
        else ("host" if pre_labels is not None and post_labels is not None else "closure")
    )
    key = key + (label_mode,)
    fn = _JIT_CACHE.get(key)
    if fn is None:

        @jax.jit
        def fn(pre, post, pre_tid, post_tid, pre_lab, post_lab):
            out = {}
            alive2 = {}
            labs = {"pre": pre_lab, "post": post_lab}
            for name, b, tid in (("pre", pre, pre_tid), ("post", post, post_tid)):
                adj = build_adjacency(b.edge_src, b.edge_dst, b.edge_mask, v)
                adj = lax.with_sharding_constraint(adj, spec_adj)
                out[f"{name}_holds"] = mark_condition_holds(
                    adj, b.is_goal, b.table_id, b.node_mask, tid, num_tables
                )
                adj_c, alive = clean_masks(adj, b.is_goal, b.node_mask)
                # Edge rewiring always by O(V^2) scatters — no V^3 matmul.
                adj2, alive2[name], type2 = collapse_chains(
                    adj_c,
                    b.is_goal,
                    b.type_id,
                    alive,
                    comp_doubling=label_mode == "doubling",
                    comp_labels=labs[name] if label_mode == "host" else None,
                    rewire="scatter",
                )
                out[f"{name}_adj_clean"] = lax.with_sharding_constraint(adj2, spec_adj)
                out[f"{name}_alive"] = alive2[name]
                out[f"{name}_type"] = type2
            achieved = out["pre_holds"].any(axis=-1)
            out["achieved_pre"] = achieved
            bits, min_depth = proto_rule_bits(
                out["post_adj_clean"],
                post.is_goal,
                alive2["post"],
                post.table_id,
                achieved,
                num_tables,
                proto_depth,
                use_closure=False,
            )
            out["proto_bits"] = bits
            out["proto_min_depth"] = min_depth
            out["proto_present"] = all_rule_bits(
                post.is_goal, alive2["post"], post.table_id, num_tables
            )
            if pack_out:
                from nemo_tpu.models.pipeline_model import (
                    GIANT_PACK_LAYOUT,
                    fold_packed_summary,
                )

                fold_packed_summary(out, GIANT_PACK_LAYOUT)
            return out

        _JIT_CACHE[key] = fn

    def shard(b):
        import dataclasses

        return dataclasses.replace(
            b,
            is_goal=jax.device_put(b.is_goal, spec_node),
            table_id=jax.device_put(b.table_id, spec_node),
            label_id=jax.device_put(b.label_id, spec_node),
            type_id=jax.device_put(b.type_id, spec_node),
            node_mask=jax.device_put(b.node_mask, spec_node),
        )

    if pre_labels is None:
        # Unused by the non-"host" traces; a zero plane keeps the jit
        # signature uniform across the variants.
        pre_labels = jnp.zeros(pre.is_goal.shape, dtype=jnp.int32)
    if post_labels is None:
        post_labels = jnp.zeros(post.is_goal.shape, dtype=jnp.int32)
    # jnp.asarray + device_put: no host round-trip when the planes already
    # live on device (the executor converts kernel inputs eagerly; a numpy
    # coercion here would cost two synchronous tunnel transfers per run).
    return fn(
        shard(pre),
        shard(post),
        pre_tid,
        post_tid,
        jax.device_put(jnp.asarray(pre_labels, dtype=jnp.int32), spec_node),
        jax.device_put(jnp.asarray(post_labels, dtype=jnp.int32), spec_node),
    )
