"""Ring-scheduled frontier propagation for node-sharded giant graphs.

This is the framework's long-context / sequence-parallel analog (SURVEY.md
§5): the reference's only 'long' dimension is deep @next chains, which it
contracts; but a provenance graph too large for one chip's HBM needs its
node dimension sharded.  ring_reach shards the adjacency by column blocks
(each device owns the in-edges of its node block) and the frontier by row
blocks; each of the K ring steps multiplies the local frontier chunk against
the matching row-block of the local adjacency shard and ppermutes the chunk
to the next device — the same stationary-weights / moving-activations
schedule as ring attention, riding ICI neighbor links with no all-gather of
the full frontier.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nemo_tpu.utils.jax_config import axis_size, pcast_varying, shard_map

# Device topology comes from THE mesh module (parallel/mesh.py): the ring
# path shares one device-grid source with the production run mesh and the
# multi-host hybrid mesh, so a topology change lands in one place.
from .mesh import NODE_AXIS, make_node_mesh  # noqa: F401  (re-export)


def _ring_step_body(frontier_chunk, adj_shard, axis_name):
    """One full ring rotation: accumulate new-frontier contributions for this
    device's node block from every frontier chunk passing by."""
    n_dev = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    chunk = frontier_chunk  # [Vb] bool, row-block (axis_index) of the frontier
    # Mark the accumulator as device-varying so the ring loop's carry type is
    # stable under shard_map's varying-axes checks (a no-op on jax versions
    # without the check — utils/jax_config.py:pcast_varying).
    acc = pcast_varying(
        jnp.zeros((adj_shard.shape[1],), dtype=jnp.float32), axis_name
    )

    def body(i, carry):
        chunk, acc = carry
        # The chunk currently held started at device (my + i) mod n_dev, so it
        # covers that row block of the global frontier; multiply against the
        # matching row block of our column shard.
        src_block = (my + i) % n_dev
        vb = chunk.shape[0]
        rows = lax.dynamic_slice_in_dim(adj_shard, src_block * vb, vb, axis=0)
        acc = acc + chunk.astype(jnp.bfloat16) @ rows.astype(jnp.bfloat16)
        # Pass our chunk around the ring (receive from the next device).
        chunk = lax.ppermute(
            chunk, axis_name, [(j, (j - 1) % n_dev) for j in range(n_dev)]
        )
        return chunk, acc

    chunk, acc = lax.fori_loop(0, n_dev, body, (chunk, acc))
    return acc > 0.5


def closure_sharded(mesh: Mesh, adjacency: jnp.ndarray) -> jnp.ndarray:
    """Reflexive-transitive closure of ONE giant graph, node-sharded.

    The adjacency's columns are sharded over the mesh and the log2(V)
    boolean-matmul squarings (ops/adjacency.py:closure's XLA chain) run SPMD:
    GSPMD partitions each [V,V]x[V,V] product, with the contraction's partial
    sums riding ICI — the path for a single provenance graph whose dense
    adjacency exceeds one chip's HBM.  Per-run batched graphs never need
    this; they shard over the run axis instead (parallel/mesh.py).
    """
    from nemo_tpu.ops.adjacency import closure

    v = adjacency.shape[-1]
    if v % mesh.devices.size:
        raise ValueError(f"V={v} not divisible by mesh size {mesh.devices.size}")
    sharded = jax.device_put(adjacency, NamedSharding(mesh, P(None, NODE_AXIS)))
    fn = jax.jit(partial(closure, impl="xla"))  # pallas closure can't shard
    return fn(sharded)


def ring_reach(mesh: Mesh, adjacency: jnp.ndarray, start: jnp.ndarray, steps: int) -> jnp.ndarray:
    """BFS reachability (>=0 hops) over a node-sharded graph.

    adjacency: [V, V] (will be column-sharded over the mesh);
    start: [V] bool (row-sharded).  V must divide evenly by mesh size.
    Returns the reachable-set mask [V].
    """
    v = adjacency.shape[0]
    n_dev = mesh.devices.size
    if v % n_dev:
        raise ValueError(f"V={v} not divisible by mesh size {n_dev}")

    @partial(
        shard_map(),
        mesh=mesh,
        in_specs=(P(None, NODE_AXIS), P(NODE_AXIS)),
        out_specs=P(NODE_AXIS),
    )
    def run(adj_shard, start_chunk):  # adj [V, Vb], start [Vb]
        def body(_, reach_chunk):
            new = _ring_step_body(reach_chunk, adj_shard, NODE_AXIS)
            return reach_chunk | new

        return lax.fori_loop(0, steps, body, start_chunk)

    adj_sharded = jax.device_put(adjacency, NamedSharding(mesh, P(None, NODE_AXIS)))
    start_sharded = jax.device_put(start, NamedSharding(mesh, P(NODE_AXIS)))
    return run(adj_sharded, start_sharded)
