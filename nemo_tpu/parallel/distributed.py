"""Multi-host scale-out: jax.distributed init + hybrid DCN x ICI meshes.

The reference has no multi-node story at all (SURVEY.md §2.3: one Go process,
one Neo4j container).  Here scale-out is the standard JAX SPMD recipe: every
host runs the same program, `jax.distributed.initialize` wires the processes
into one runtime, and the run batch is sharded over a 2-D (dcn, ici) mesh —
the outer axis spans hosts over the data-center network, the inner axis spans
each host's chips over ICI.  XLA derives the collective topology from the
device assignment, so the cross-run prototype reductions become hierarchical
all-reduces (intra-host rings over ICI first, then one small DCN exchange),
and per-run kernels never communicate at all — the layout the scaling
playbook prescribes for pure data parallelism.

Single-process environments (tests, the virtual-device harness) get the same
code path: the hybrid mesh is just a reshape of the local devices.
"""

from __future__ import annotations

import os

import jax
from jax.sharding import PartitionSpec as P

from nemo_tpu.models.pipeline_model import BatchArrays
from nemo_tpu.parallel.mesh import (  # noqa: F401  (make_hybrid_mesh re-export)
    DCN_AXIS,
    ICI_AXIS,
    Mesh,
    make_hybrid_mesh,
    run_step_sharded,
)
from nemo_tpu.utils.jax_config import distributed_is_initialized


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize the multi-process JAX runtime when configured; returns
    whether a multi-process runtime is active.

    Configuration comes from the arguments or the standard environment
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, or a
    supported cluster environment that jax.distributed auto-detects).  A
    plain single-process run is left untouched — calling this is always safe.
    """
    if distributed_is_initialized():
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    env_procs = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(env_procs) if env_procs else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address is None and num_processes is None:
        return False  # single-process: nothing to initialize
    if coordinator_address is None or num_processes is None:
        # A stray half-configuration (e.g. a shared env file exporting only
        # one of the two) must not crash a plain single-process run.
        import warnings

        have = "JAX_COORDINATOR_ADDRESS" if coordinator_address else "JAX_NUM_PROCESSES"
        need = "JAX_NUM_PROCESSES" if coordinator_address else "JAX_COORDINATOR_ADDRESS"
        warnings.warn(
            f"{have} set without {need}; ignoring and staying single-process",
            stacklevel=2,
        )
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def analysis_step_hybrid(
    mesh: Mesh, pre: BatchArrays, post: BatchArrays, static: dict
) -> dict:
    """The flagship analysis step with the run batch data-parallel over BOTH
    mesh axes (runs split across hosts, then across each host's chips).

    Same semantics as parallel/mesh.py:analysis_step_sharded; the only
    difference is the 2-D device layout, which makes XLA lower the prototype
    intersection/union reductions hierarchically (ICI ring + DCN exchange)
    and broadcast the row-0 good graph the same way.
    """
    return run_step_sharded(mesh, P((DCN_AXIS, ICI_AXIS)), pre, post, static)
