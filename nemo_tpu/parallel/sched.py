"""Cost-model-driven heterogeneous work-stealing scheduler (ISSUE 7).

PR 3 gave every joint bucket a per-bucket ROUTE (dense device dispatch vs
the sparse CSR host engine, backend/jax_backend.py:_analysis_route) but
executed the routed buckets one at a time: while a device dispatch runs,
the host cores idle, and vice versa.  This module turns the route decision
into a multi-lane schedule:

  * **device lane**: one worker thread draining buckets into the (now
    mesh-sharded) fused executor dispatch — serialized per device, which is
    exactly what the accelerator wants;
  * **host lane**: one worker thread draining buckets into the sparse-CSR
    host engine (ops/sparse_host.py);
  * **sparse_device lane** (ISSUE 10): one worker thread draining buckets
    into the sparse-CSR DEVICE engine (ops/sparse_device.py via the
    sparse_fused executor verb) — offered per job via ``Job.lanes`` where
    a real accelerator backs it, priced by the same LaneModel EWMA
    feedback, so the scheduler can mix dense-device / sparse-device /
    sparse-host per bucket.

Buckets are assigned a PREFERRED lane by a cost model — wall ≈ fixed +
unit x work per lane, seeded from the PR-3/PR-4 measured constants (the
sparse engine's ~1 us/work-unit and the dispatch-crossover budget
NEMO_ANALYSIS_HOST_WORK) and refined per (verb, V, E) shape class by an
EWMA over the walls this process actually measured, so a mispredicted
bucket corrects the predictions for the rest of the session.  The device
lane additionally consults the PR-4 per-signature cost table through an
optional ``hint`` callable (FLOPs-derived wall for a signature costed in a
previous corpus but not yet measured by this scheduler).

An idle lane STEALS the next queued unpinned bucket from the other lane's
tail rather than waiting — so a corpus whose cost model mispredicts still
finishes at the speed of both tiers combined.  Jobs pinned by an explicit
NEMO_ANALYSIS_IMPL (or the platform resolution) never migrate: a forced
route is an operator decision, not a preference.

Determinism: results land by job index, so callers see bucket order
independent of completion order, and each bucket's result is bit-identical
on either lane (the sparse/dense parity suites pin that) — scheduling
changes WHEN work runs, never what it produces.

Every decision is recorded: ``analysis.sched.*`` metrics (dispatch/steal
counters per lane, per-lane wall histograms), one record per job in a
process-global table exported to telemetry.json, and the
``analysis:sched`` span wrapping each drain.

Knobs: NEMO_SCHED=auto|on|off (auto = schedule when >1 job; off = the
serial pre-PR loop, kept as the debugging fallback), NEMO_SCHED_HOST_UNIT /
NEMO_SCHED_DEVICE_UNIT (seconds per work unit), NEMO_SCHED_DEVICE_FIXED
(seconds per dispatch; default derives from the crossover budget so an
unmeasured scheduler reproduces PR 3's routing exactly).

Fault tolerance (ISSUE 9): the two lanes compute IDENTICAL results, which
makes the host lane a hot standby for the device lane.  A device-lane
failure (XLA/OOM error, a dead sidecar, a dispatch past the hard
``NEMO_DISPATCH_TIMEOUT_S`` deadline — the escalation past the log-only
``NEMO_SLOW_DISPATCH_MS`` watchdog) re-runs the job on the sparse-host
lane after a jittered backoff (``analysis.sched.failover`` + a route
record with reason "failover") instead of failing the request.  Repeated
device failures trip a process-global CIRCUIT BREAKER
(``sched.breaker.*`` metrics): while OPEN, planning short-circuits every
non-operator-forced job to the host lane (degraded host-only mode); after
``NEMO_BREAKER_COOLDOWN_S`` one HALF-OPEN probe job tries the device
again and a success closes the breaker.  An operator-FORCED device route
(NEMO_ANALYSIS_IMPL=dense) never fails over and never short-circuits: an
explicit pin is a correctness decision, and masking its failures would
hide exactly what the operator is testing.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from nemo_tpu import obs
from nemo_tpu.utils.backoff import FAILOVER_POLICY
from nemo_tpu.utils.env import (
    breaker_cooldown_s,
    breaker_failures,
    dispatch_timeout_s,
)

_log = obs.log.get_logger("nemo.sched")

#: All schedulable lanes, in tie-break preference order.  "sparse_device"
#: (ISSUE 10) is the sparse-CSR device engine (ops/sparse_device.py via
#: the sparse_fused executor verb): a THIRD lane the cost model may mix
#: with the dense device dispatch and the sparse host engine per bucket.
#: Jobs opt into it via Job.lanes — the backend offers it only where a
#: real accelerator backs it — so a scheduler built from two-lane models
#: behaves exactly as before.
LANES = ("device", "sparse_device", "host")

#: route vocabulary of the analysis.route records, per lane (the scheduler
#: speaks "lane", the route records speak the PR-3 sparse/dense vocabulary,
#: extended with the ISSUE-10 sparse_device route).
ROUTE_OF_LANE = {"device": "dense", "host": "sparse", "sparse_device": "sparse_device"}
LANE_OF_ROUTE = {route: lane for lane, route in ROUTE_OF_LANE.items()}

#: Lanes that execute on the accelerator (or its tunnel): the circuit
#: breaker, the dispatch deadline, and the failover machinery treat them
#: as one health domain — a sick device is sick for both the dense and the
#: sparse-CSR programs, and both fail over to the bit-identical host lane.
DEVICE_SIDE_LANES = frozenset({"device", "sparse_device"})


def sched_env() -> str:
    """Parse + validate NEMO_SCHED.  Loud on junk (the NEMO_ANALYSIS_IMPL
    policy): a typo silently resolving to auto would change execution
    concurrency in exactly the dimension the operator was pinning."""
    v = os.environ.get("NEMO_SCHED", "auto").strip().lower()
    if v == "auto":
        return "auto"
    if v in ("1", "true", "yes", "on"):
        return "on"
    if v in ("0", "false", "no", "off"):
        return "off"
    raise ValueError(f"NEMO_SCHED={v!r} (expected auto, on, or off)")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None
    if val <= 0:
        raise ValueError(f"{name}={val} must be > 0")
    return val


def _profile_value(name: str, seeded: float) -> float:
    """Measured platform-profile value for one constant, or the seeded
    default — the middle rung of the env > profile > seeded precedence
    (platform/profile.py).  Callers pass the result as _env_float's
    default, so an explicit env var still always wins.  Defensive: the
    scheduler must keep working when the profile subsystem is absent or
    broken (it is observability-adjacent, never load-bearing)."""
    try:
        from nemo_tpu.platform import profile as _pp

        v = _pp.profile_value(name)
    except Exception:  # lint: allow-silent-except — a broken profile store must degrade to seeded constants, not sink scheduling (docstring)
        return seeded
    return seeded if v is None else float(v)


@dataclass
class Job:
    """One schedulable bucket: identity for the cost model (verb, rows, V,
    E, work = rows x (V+E) — the same work unit as the PR-3 crossover) plus
    the execution callable.  ``execute(lane, reason, stolen)`` runs the
    bucket on the named lane and returns its result dict; the callable owns
    route recording and spans so records look identical to the serial path.
    ``pinned`` names the only lane allowed to run this job (a forced or
    platform route); ``reason`` is the route reason recorded when the job
    runs on its planned lane ("sched" for cost-model preferences)."""

    index: int
    verb: str
    rows: int
    v: int
    e: int
    work: int
    execute: Callable[[str, str, bool], dict]
    pinned: str | None = None
    reason: str = "sched"
    #: Who submitted the job — "pipeline" for the analysis drain, "serve"
    #: for the serving tier's cross-request merged kernel launches
    #: (nemo_tpu/serve/batch.py) — recorded per decision so telemetry can
    #: split a sidecar's own corpus work from its serving traffic.
    source: str = "pipeline"
    #: Set True BY the execute callable when the measured wall includes a
    #: one-off cost that must not feed the cost model — a jit compile
    #: (seconds) folded into a warm-execution EWMA (tens of ms) would price
    #: every later same-class bucket off the device lane for the whole
    #: session.  The scheduler still records the wall; it skips observe().
    wall_tainted: bool = False
    #: Lanes this job's execute closure implements.  The default is the
    #: two-lane pre-ISSUE-10 contract; the backend adds "sparse_device"
    #: where the CSR device engine is available, and only lanes in this
    #: tuple are considered for unpinned planning or stealing (a pin
    #: bypasses it — pinned jobs run their lane regardless).
    lanes: tuple = ("device", "host")
    #: PADDED batch width the device dispatch materializes (run-axis
    #: bucket + shard multiple); 0 = unknown (falls back to `rows`).  The
    #: device-lane FLOPs hint scales by THIS — the dispatch pays for the
    #: padded program, not the real-run count
    #: (backend/jax_backend.py:sched_device_hint).
    rows_dispatch: int = 0


class LaneModel:
    """Per-lane wall-clock predictor: wall ≈ fixed + unit x work, with a
    per-(verb, V, E) shape-class EWMA of measured per-row walls taking over
    once the lane has actually executed that class — measured walls beat
    any static model, and the shape class is what the jit cache keys on, so
    walls within a class are comparable.  ``hint(job)`` (optional) supplies
    a prediction between those two: consulted when the class is unmeasured,
    e.g. the PR-4 cost table's FLOPs estimate for a signature compiled in
    an earlier corpus."""

    def __init__(
        self,
        fixed_s: float,
        unit_s: float,
        alpha: float = 0.5,
        hint: Callable[[Job], float | None] | None = None,
    ) -> None:
        self.fixed_s = float(fixed_s)
        self.unit_s = float(unit_s)
        self.alpha = float(alpha)
        self.hint = hint
        #: (verb, v, e) -> EWMA seconds per row, measured by this process.
        self.per_row: dict[tuple[str, int, int], float] = {}

    def predict(self, job: Job) -> float:
        per_row = self.per_row.get((job.verb, job.v, job.e))
        if per_row is not None:
            return self.fixed_s + per_row * job.rows
        if self.hint is not None:
            h = self.hint(job)
            if h is not None:
                return self.fixed_s + float(h)
        return self.fixed_s + self.unit_s * job.work

    def observe(self, job: Job, wall_s: float) -> None:
        """Feed one measured execution back into the model (the feedback
        loop that corrects a mispredicted bucket for the whole session)."""
        variable = max(wall_s - self.fixed_s, 1e-9)
        per_row = variable / max(job.rows, 1)
        key = (job.verb, job.v, job.e)
        old = self.per_row.get(key)
        self.per_row[key] = (
            per_row if old is None else (1 - self.alpha) * old + self.alpha * per_row
        )
        unit = variable / max(job.work, 1)
        self.unit_s = (1 - self.alpha) * self.unit_s + self.alpha * unit


def default_models(
    host_work_budget: int | None = None,
    device_hint: Callable[[Job], float | None] | None = None,
) -> dict[str, LaneModel]:
    """Lane models seeded so an UNMEASURED scheduler reproduces the PR-3
    crossover: the host lane costs the sparse engine's measured ~1 us per
    work unit (BENCH sparse tier), and the device lane pays a fixed
    dispatch cost equal to the crossover budget's worth of host work —
    predictions then cross at exactly work ≈ NEMO_ANALYSIS_HOST_WORK, the
    measured break-even PR 3 shipped.  Feedback refines both from there.

    With a measured platform profile active (ISSUE 19), every seed below
    resolves env > profile > seeded — the profile's fitted walls replace
    the hand-tuned constants unless the operator's env var pins them."""
    host_unit = _env_float("NEMO_SCHED_HOST_UNIT", _profile_value("sched_host_unit", 1e-6))
    device_unit = _env_float(
        "NEMO_SCHED_DEVICE_UNIT", _profile_value("sched_device_unit", 5e-8)
    )
    budget = host_work_budget
    if budget is None:
        env = os.environ.get("NEMO_ANALYSIS_HOST_WORK")
        budget = (
            int(env)
            if env is not None
            else int(_profile_value("analysis_host_work", 100000))
        )
    # fixed + unit_d*budget == unit_h*budget: the two lines intersect at
    # exactly the budget (a fixed of budget*unit_h alone would put the
    # break-even ~unit_d/unit_h above it).  A measured profile supplies
    # its fitted intercept directly instead of the derived seed.
    device_fixed = _env_float(
        "NEMO_SCHED_DEVICE_FIXED",
        _profile_value(
            "sched_device_fixed", budget * max(host_unit - device_unit, 1e-12)
        ),
    )
    # The sparse-device lane (ISSUE 10) pays the same per-dispatch fixed
    # cost class (RTT + program launch) but its per-unit work is
    # E-proportional frontier waves — seeded between the dense device and
    # the host engine so an unmeasured scheduler prefers the dense MXU
    # dispatch (the measured small-V winner) and lets the EWMA feedback
    # promote the sparse lane where it actually wins.
    sparse_device_unit = _env_float(
        "NEMO_SCHED_SPARSE_DEVICE_UNIT",
        _profile_value("sched_sparse_device_unit", 2.5e-7),
    )
    return {
        "device": LaneModel(device_fixed, device_unit, hint=device_hint),
        "sparse_device": LaneModel(device_fixed, sparse_device_unit),
        "host": LaneModel(0.0, host_unit),
    }


# ---------------------------------------------------------------------------
# lane failure classification + circuit breaker (ISSUE 9)
# ---------------------------------------------------------------------------


class DispatchTimeout(TimeoutError):
    """A device-lane dispatch exceeded NEMO_DISPATCH_TIMEOUT_S and was
    abandoned (the wedged thread cannot be cancelled mid-XLA; it is left
    behind as a daemon and its eventual result discarded)."""


#: Exception type-name fragments that read as INFRASTRUCTURE failures of
#: the device lane (XLA runtime, jax backend, the sidecar RPC stack) as
#: opposed to programming errors, which must propagate.
_LANE_FAILURE_TYPES = (
    "XlaRuntimeError",
    "JaxRuntimeError",
    "RpcError",
    "SidecarError",
    "ChaosFault",
    "InternalError",
)


def is_lane_failure(ex: BaseException) -> bool:
    """Whether ``ex`` is a DEVICE-LANE infrastructure failure the scheduler
    may recover from by re-running the job on the host lane.  Conservative:
    anything that smells like a bug in our own code (ValueError, KeyError,
    assertion...) propagates — failing over a deterministic bug would just
    recompute the crash more slowly, or worse, mask it."""
    if isinstance(ex, (DispatchTimeout, MemoryError)):
        return True
    for klass in type(ex).__mro__:
        if any(frag in klass.__name__ for frag in _LANE_FAILURE_TYPES):
            return True
    if isinstance(ex, RuntimeError):
        msg = str(ex).lower()
        return (
            "resource_exhausted" in msg
            or "out of memory" in msg
            or "resource exhausted" in msg
        )
    return False


class CircuitBreaker:
    """Closed / open / half-open breaker over the device lane.

    ``record_failure`` past the consecutive-failure threshold
    (``NEMO_BREAKER_FAILURES``) trips it OPEN: ``allow()`` then answers
    False (planning short-circuits to the host lane — degraded host-only
    mode, requests keep succeeding) until ``NEMO_BREAKER_COOLDOWN_S`` has
    passed, when exactly ONE caller is let through HALF-OPEN as a probe; a
    probe success closes the breaker, a failure re-opens it for another
    cooldown.  All transitions are metrics (``sched.breaker.*``) and
    structured logs; state is also a gauge (0 closed, 1 open, 2 half-open)
    so a degraded sidecar is visible on the Prometheus surface."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self, failures: int | None = None, cooldown_s: float | None = None
    ) -> None:
        self.failures = failures if failures is not None else breaker_failures()
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None else breaker_cooldown_s()
        )
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_at = 0.0

    def _gauge(self) -> None:
        obs.metrics.gauge(
            "sched.breaker.state",
            {self.CLOSED: 0, self.OPEN: 1, self.HALF_OPEN: 2}[self.state],
        )

    def allow(self) -> bool:
        """May the device lane take another job right now?  Consumes the
        half-open probe when one is due — callers that only want to LOOK
        use :meth:`peek` (no transitions, no counters)."""
        with self._lock:
            now = time.monotonic()
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if now - self._opened_at >= self.cooldown_s:
                    self.state = self.HALF_OPEN
                    self._probe_at = now
                    self._gauge()
                    obs.metrics.inc("sched.breaker.probe")
                    _log.info("sched.breaker_probe", cooldown_s=self.cooldown_s)
                    return True  # exactly this caller probes
                obs.metrics.inc("sched.breaker.short_circuit")
                return False
            # HALF_OPEN: one probe is in flight; everyone else stays on the
            # host lane until it reports.  A probe can be LOST without a
            # device execution reporting back (the probe job was stolen by
            # the host lane, or the probing worker found nothing left to
            # run) — re-arm after another cooldown so a long-lived process
            # can never wedge in HALF_OPEN/host-only forever.
            if now - self._probe_at >= self.cooldown_s:
                self._probe_at = now
                obs.metrics.inc("sched.breaker.probe")
                _log.info("sched.breaker_probe_rearmed", cooldown_s=self.cooldown_s)
                return True
            obs.metrics.inc("sched.breaker.short_circuit")
            return False

    def peek(self) -> bool:
        """Whether :meth:`allow` WOULD grant right now — no state
        transition, no metrics.  The device worker's wait loop polls this
        every ~10 ms; counting those polls as short-circuits would turn a
        per-job degradation metric into a spin counter."""
        with self._lock:
            now = time.monotonic()
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                return now - self._opened_at >= self.cooldown_s
            return now - self._probe_at >= self.cooldown_s

    def record_failure(self) -> None:
        tripped = 0
        with self._lock:
            self._consecutive += 1
            obs.metrics.inc("sched.breaker.failures")
            if self.state == self.HALF_OPEN or (
                self.state == self.CLOSED and self._consecutive >= self.failures
            ):
                self.state = self.OPEN
                self._opened_at = time.monotonic()
                self._gauge()
                obs.metrics.inc("sched.breaker.trip")
                _log.error(
                    "sched.breaker_open",
                    consecutive_failures=self._consecutive,
                    cooldown_s=self.cooldown_s,
                    detail="device lane degraded; routing host-only until a "
                    "half-open probe succeeds",
                )
                tripped = self._consecutive
        if tripped:
            # Flight-recorder dump OUTSIDE the breaker lock (bundle IO must
            # not serialize against allow()/peek() on the dispatch path).
            obs.flight.trigger(
                "breaker_trip", consecutive_failures=tripped,
                cooldown_s=self.cooldown_s,
            )

    def record_success(self) -> None:
        with self._lock:
            closed = self.state != self.CLOSED
            self.state = self.CLOSED
            self._consecutive = 0
            if closed:
                self._gauge()
                obs.metrics.inc("sched.breaker.close")
                _log.info("sched.breaker_closed")


#: Pin reasons whose execute closures implement the host lane too (the
#: jax_backend fused/giant closures): the breaker may reroute them and a
#: device failure may re-run them on the host lane.  "mem" (ISSUE 10) pins
#: a bucket off the DENSE device lane because its [B,V,V] footprint would
#: cross the memory watermark — the bit-identical host engine is a legal
#: degraded target, the dense device lane is not.  NOT "forced" (an
#: operator pin is a correctness decision whose failures must surface) and
#: NOT "serve_batch" (the serving tier's merged launches are device-only
#: closures — handing them a host lane would still dispatch on the broken
#: device while recording host).
_DUAL_LANE_PIN_REASONS = frozenset({"platform", "giant_impl", "mem"})


def _may_reroute(job: Job) -> bool:
    """May the breaker/failover machinery run this job on the OTHER lane?
    True only when the job's execute closure actually honors the lane
    argument with a bit-identical host implementation."""
    return job.pinned is None or job.reason in _DUAL_LANE_PIN_REASONS


#: Process-global device-lane breaker: device health is a property of the
#: process's accelerator (or its tunnel), not of one corpus — a long-lived
#: sidecar that tripped it stays host-only across requests until a probe
#: heals it.
_DEVICE_BREAKER: CircuitBreaker | None = None
_BREAKER_LOCK = threading.Lock()


def device_breaker() -> CircuitBreaker:
    global _DEVICE_BREAKER
    with _BREAKER_LOCK:
        if _DEVICE_BREAKER is None:
            _DEVICE_BREAKER = CircuitBreaker()
        return _DEVICE_BREAKER


def reset_device_breaker() -> None:
    """Forget breaker state (tests, or an operator bouncing after fixing
    the tunnel without waiting out the cooldown)."""
    global _DEVICE_BREAKER
    with _BREAKER_LOCK:
        _DEVICE_BREAKER = None


#: Process-global lane models: measured walls persist across corpora in one
#: session (a long-lived sidecar keeps learning), like the jit cache.
_SESSION_MODELS: dict[str, LaneModel] | None = None
#: Process-global decision table exported to telemetry.json (bounded like
#: the metrics registry's series cap; drops are impossible — deque evicts).
_RECORDS: deque = deque(maxlen=512)
_RECORDS_LOCK = threading.Lock()


def session_models(
    host_work_budget: int | None = None,
    device_hint: Callable[[Job], float | None] | None = None,
) -> dict[str, LaneModel]:
    global _SESSION_MODELS
    if _SESSION_MODELS is None:
        _SESSION_MODELS = default_models(host_work_budget, device_hint)
        # Cross-session scheduler memory (ISSUE 19): seed the fresh models'
        # per-(verb,V,E) EWMA tables from the platform profile's folded-back
        # walls, and register the shutdown fold-back.  Best-effort — the
        # scheduler never depends on the profile store being healthy.
        try:
            from nemo_tpu.platform import profile as _pp

            _pp.warm_start(_SESSION_MODELS)
        except Exception:  # lint: allow-silent-except — a broken profile store must degrade to cold models, not sink scheduling (docstring)
            pass
    elif device_hint is not None and _SESSION_MODELS["device"].hint is None:
        _SESSION_MODELS["device"].hint = device_hint
    return _SESSION_MODELS


def reset_session_models() -> None:
    """Forget learned walls (tests, and operators bouncing a bad model)."""
    global _SESSION_MODELS
    _SESSION_MODELS = None
    with _RECORDS_LOCK:
        _RECORDS.clear()


def sched_snapshot() -> list[dict]:
    """The decision table as JSON-able records (newest last) — the
    telemetry.json `sched` section reads this."""
    with _RECORDS_LOCK:
        return [dict(r) for r in _RECORDS]


class HeterogeneousScheduler:
    """Two-lane work-stealing executor over a job list.

    ``run(jobs)`` drains the jobs on one worker thread per lane and returns
    results in job-index order.  Planned lanes come from the cost model
    (or the job's pin); an idle lane steals the next UNPINNED job from the
    other lane's tail (the far end — the victim lane keeps its head-of-line
    locality).  The first worker exception aborts both lanes and re-raises
    in the caller."""

    def __init__(self, models: dict[str, LaneModel] | None = None) -> None:
        self.models = models or session_models()
        #: Worker lanes, in LANES preference order: one worker thread per
        #: modeled lane.  Two-lane model dicts (the pre-ISSUE-10 contract,
        #: still what the unit suites build) get exactly the old two-lane
        #: scheduler; the production session_models add sparse_device.
        self.lanes = tuple(l for l in LANES if l in self.models) or tuple(self.models)
        self.steals = {lane: 0 for lane in self.lanes}
        self.dispatched = {lane: 0 for lane in self.lanes}
        self.failovers = 0
        self.breaker = device_breaker()
        #: Shared jittered-backoff session for this drain's failovers
        #: (utils/backoff.py — budget-bounded, so a burst of failing
        #: device jobs cannot stall the whole drain sleeping).
        self._backoff = FAILOVER_POLICY.session()

    def plan(self, job: Job) -> tuple[str, str, dict]:
        """(lane, reason, predictions) for one job.  An OPEN device-lane
        circuit breaker short-circuits every non-operator-forced device
        plan to the host lane (degraded host-only mode); a forced route
        keeps the device — an explicit pin is a correctness decision, and
        its failure should be seen, not masked."""
        candidates = [l for l in self.lanes if l in job.lanes] or list(self.lanes)
        preds = {lane: self.models[lane].predict(job) for lane in candidates}
        if job.pinned:
            lane, reason = job.pinned, job.reason
        else:
            # Min predicted wall; ties break in LANES order (device first —
            # the pre-ISSUE-10 behavior for the two-lane case).
            lane = min(candidates, key=lambda l: (preds[l], candidates.index(l)))
            reason = "sched"
        if lane in DEVICE_SIDE_LANES and _may_reroute(job) and not self.breaker.allow():
            return "host", "breaker", preds
        return lane, reason, preds

    def _execute_deadline(self, job: Job, lane: str, reason: str, stolen: bool):
        """Run one job, with the hard ``NEMO_DISPATCH_TIMEOUT_S`` deadline
        on DEVICE-lane executions (0 = off, the default).  A mid-XLA (or
        mid-RPC) dispatch cannot be cancelled, so a timed-out dispatch is
        ABANDONED: its thread is left behind as a daemon, its eventual
        result discarded, and :class:`DispatchTimeout` raised for the
        failover path — the escalation ladder's last rung (the
        NEMO_SLOW_DISPATCH_MS watchdog logs, this cancels + fails over)."""
        timeout = dispatch_timeout_s()
        # The lane span: a stitched client trace shows which scheduler lane
        # ran each job between the admission span and the kernel spans.
        with obs.span(f"sched:{lane}", verb=job.verb, index=job.index, reason=reason):
            if lane not in DEVICE_SIDE_LANES or not timeout:
                return job.execute(lane, reason, stolen)
            box: dict = {}
            done = threading.Event()

            def target() -> None:
                try:
                    box["res"] = job.execute(lane, reason, stolen)
                except BaseException as ex:
                    box["ex"] = ex
                finally:
                    done.set()

            t = threading.Thread(
                target=target, daemon=True, name=f"nemo-sched-dispatch-{job.index}"
            )
            t.start()
            if not done.wait(timeout):
                obs.metrics.inc("watchdog.dispatch_timeout")
                _log.error(
                    "sched.dispatch_timeout",
                    verb=job.verb,
                    index=job.index,
                    timeout_s=timeout,
                    detail="abandoning the wedged dispatch thread (daemon); "
                    "failing the job over to the host lane",
                )
                # The escalation rung IS the incident: capture the ring
                # (the wedged dispatch's spans are still in it).
                obs.flight.trigger(
                    "dispatch_watchdog", verb=job.verb, index=job.index,
                    timeout_s=timeout,
                )
                raise DispatchTimeout(
                    f"device dispatch of job {job.index} ({job.verb}) exceeded "
                    f"NEMO_DISPATCH_TIMEOUT_S={timeout}"
                )
            if "ex" in box:
                raise box["ex"]
            return box["res"]

    def run(self, jobs: list[Job], serial: bool = False) -> list[dict]:
        results: list[dict | None] = [None] * len(jobs)
        queues: dict[str, deque[Job]] = {lane: deque() for lane in self.lanes}
        plans: dict[int, tuple[str, str, dict]] = {}
        for job in jobs:
            lane, reason, preds = self.plan(job)
            if lane not in queues:
                raise ValueError(
                    f"job {job.index} planned for lane {lane!r} but the "
                    f"scheduler models only {self.lanes}"
                )
            plans[job.index] = (lane, reason, preds)
            queues[lane].append(job)
        obs.metrics.inc("analysis.sched.jobs", len(jobs))

        lock = threading.Lock()
        errors: list[BaseException] = []

        def run_one(job: Job, lane: str, stolen: bool) -> None:
            planned_lane, reason, preds = plans[job.index]
            if stolen:
                reason = "steal"
            t0 = time.perf_counter()
            failed_over = False
            try:
                res = self._execute_deadline(job, lane, reason, stolen)
                if lane in DEVICE_SIDE_LANES:
                    self.breaker.record_success()
            except BaseException as ex:
                # Lane failover (ISSUE 9): a device-lane INFRASTRUCTURE
                # failure re-runs the job on the host lane — the two lanes
                # are bit-identical, so the request degrades to host speed
                # instead of failing.  Host-lane failures, programming
                # errors, operator-FORCED device routes, and device-only
                # closures (serve-batch launches) propagate.
                if lane in DEVICE_SIDE_LANES and is_lane_failure(ex):
                    # Device health signal recorded even when the job
                    # cannot reroute (forced pin, device-only closure):
                    # its failure still means the lane is sick.
                    self.breaker.record_failure()
                    obs.metrics.inc("analysis.sched.lane_failure.device")
                if (
                    lane not in DEVICE_SIDE_LANES
                    or not _may_reroute(job)
                    or not is_lane_failure(ex)
                ):
                    raise
                with lock:
                    wait = self._backoff.delay()
                _log.warning(
                    "sched.failover",
                    index=job.index,
                    verb=job.verb,
                    error=f"{type(ex).__name__}: {ex}",
                    backoff_s=round(wait, 3) if wait else 0.0,
                    detail="re-running on the sparse-host lane",
                )
                if wait:
                    time.sleep(wait)
                obs.metrics.inc("analysis.sched.failover")
                failed_over = True
                lane, reason = "host", "failover"
                res = job.execute("host", "failover", stolen)
            wall = time.perf_counter() - t0
            with lock:
                # A failed-over wall (device failure + backoff + host run)
                # describes neither lane; keep it out of the cost models.
                if not job.wall_tainted and not failed_over:
                    self.models[lane].observe(job, wall)
                self.dispatched[lane] += 1
                if stolen:
                    self.steals[lane] += 1
                if failed_over:
                    self.failovers += 1
                results[job.index] = res
            obs.metrics.inc(f"analysis.sched.dispatch.{lane}")
            if stolen:
                obs.metrics.inc(f"analysis.sched.steal.{lane}")
            obs.metrics.observe(f"analysis.sched.wall_s.{lane}", wall)
            rec = {
                "index": job.index,
                "verb": job.verb,
                "rows": job.rows,
                "v": job.v,
                "e": job.e,
                "work": job.work,
                "lane": lane,
                "planned": planned_lane,
                "reason": reason,
                "source": job.source,
                "stolen": stolen,
                "pinned": job.pinned is not None,
                "tainted": job.wall_tainted,
                "failed_over": failed_over,
                "predicted_s": {k: round(v, 6) for k, v in preds.items()},
                "wall_s": round(wall, 6),
            }
            with _RECORDS_LOCK:
                _RECORDS.append(rec)

        def take(lane: str):
            """Pop (job, stolen) for `lane`: its own queue's head, else
            steal from another lane's tail an unpinned job whose execute
            closure implements this lane (Job.lanes).  An idle
            DEVICE-SIDE worker consults the circuit breaker before
            stealing (ISSUE 9): with the breaker open, pulling host-planned
            work onto the broken lane would bypass the degraded-mode
            routing — the worker gets the "breaker_wait" sentinel instead
            (it parks briefly and retries, so when the cooldown elapses
            mid-drain the then-allowed steal IS the half-open probe).
            None = no work left for this lane at all."""
            with lock:
                if queues[lane]:
                    return queues[lane].popleft(), False
                for other in self.lanes:
                    if other == lane:
                        continue
                    for i in range(len(queues[other]) - 1, -1, -1):
                        job = queues[other][i]
                        if job.pinned is None and lane in job.lanes:
                            # A stealable job EXISTS — only now consult the
                            # breaker (peek first: the wait loop must not
                            # consume the half-open probe, nor count its
                            # 10 ms polls as short-circuits; allow() takes
                            # the probe only when the steal really happens).
                            if lane in DEVICE_SIDE_LANES and (
                                not self.breaker.peek() or not self.breaker.allow()
                            ):
                                return "breaker_wait"
                            del queues[other][i]
                            return job, True
            return None

        # A job list pinned entirely to ONE lane has no concurrency to win
        # (stealing is forbidden, the other lane would idle-exit), so drain
        # it inline on the caller's thread — keeping kernel spans nested
        # under the caller's phase spans in the Perfetto view, exactly like
        # the serial loop.  The platform-routed CPU path (everything pinned
        # host) takes this branch.
        pinned_lanes = {job.pinned for job in jobs}
        if None not in pinned_lanes and len(pinned_lanes) == 1:
            serial = True
        if serial:
            # The NEMO_SCHED=off fallback (and the single-lane case): same
            # plans, same records, no threads — index order, planned lane.
            for job in jobs:
                run_one(job, plans[job.index][0], False)
            return results  # type: ignore[return-value]

        def worker(lane: str) -> None:
            while not errors:
                nxt = take(lane)
                if nxt is None:
                    return
                if nxt == "breaker_wait":
                    # Open breaker, host still draining: park instead of
                    # exiting so this worker is around to probe the device
                    # once the cooldown elapses (bounded spin — the host
                    # lane empties its queue regardless).
                    time.sleep(0.01)
                    continue
                job, stolen = nxt
                try:
                    run_one(job, lane, stolen)
                except BaseException as ex:  # propagate to the caller
                    with lock:
                        errors.append(ex)
                    return

        with obs.span("analysis:sched", jobs=len(jobs)):
            threads = [
                threading.Thread(target=worker, args=(lane,), name=f"nemo-sched-{lane}")
                for lane in self.lanes
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errors:
            raise errors[0]
        missing = [j.index for j in jobs if results[j.index] is None]
        if missing:  # a lane died mid-drain without recording an exception
            raise RuntimeError(f"scheduler dropped jobs {missing}")
        return results  # type: ignore[return-value]
