"""Cost-model-driven heterogeneous work-stealing scheduler (ISSUE 7).

PR 3 gave every joint bucket a per-bucket ROUTE (dense device dispatch vs
the sparse CSR host engine, backend/jax_backend.py:_analysis_route) but
executed the routed buckets one at a time: while a device dispatch runs,
the host cores idle, and vice versa.  This module turns the route decision
into a two-lane schedule:

  * **device lane**: one worker thread draining buckets into the (now
    mesh-sharded) fused executor dispatch — serialized per device, which is
    exactly what the accelerator wants;
  * **host lane**: one worker thread draining buckets into the sparse-CSR
    host engine (ops/sparse_host.py).

Buckets are assigned a PREFERRED lane by a cost model — wall ≈ fixed +
unit x work per lane, seeded from the PR-3/PR-4 measured constants (the
sparse engine's ~1 us/work-unit and the dispatch-crossover budget
NEMO_ANALYSIS_HOST_WORK) and refined per (verb, V, E) shape class by an
EWMA over the walls this process actually measured, so a mispredicted
bucket corrects the predictions for the rest of the session.  The device
lane additionally consults the PR-4 per-signature cost table through an
optional ``hint`` callable (FLOPs-derived wall for a signature costed in a
previous corpus but not yet measured by this scheduler).

An idle lane STEALS the next queued unpinned bucket from the other lane's
tail rather than waiting — so a corpus whose cost model mispredicts still
finishes at the speed of both tiers combined.  Jobs pinned by an explicit
NEMO_ANALYSIS_IMPL (or the platform resolution) never migrate: a forced
route is an operator decision, not a preference.

Determinism: results land by job index, so callers see bucket order
independent of completion order, and each bucket's result is bit-identical
on either lane (the sparse/dense parity suites pin that) — scheduling
changes WHEN work runs, never what it produces.

Every decision is recorded: ``analysis.sched.*`` metrics (dispatch/steal
counters per lane, per-lane wall histograms), one record per job in a
process-global table exported to telemetry.json, and the
``analysis:sched`` span wrapping each drain.

Knobs: NEMO_SCHED=auto|on|off (auto = schedule when >1 job; off = the
serial pre-PR loop, kept as the debugging fallback), NEMO_SCHED_HOST_UNIT /
NEMO_SCHED_DEVICE_UNIT (seconds per work unit), NEMO_SCHED_DEVICE_FIXED
(seconds per dispatch; default derives from the crossover budget so an
unmeasured scheduler reproduces PR 3's routing exactly).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from nemo_tpu import obs

_log = obs.log.get_logger("nemo.sched")

LANES = ("device", "host")

#: route vocabulary of the analysis.route records, per lane (the scheduler
#: speaks "lane", the route records speak the PR-3 sparse/dense vocabulary).
ROUTE_OF_LANE = {"device": "dense", "host": "sparse"}


def sched_env() -> str:
    """Parse + validate NEMO_SCHED.  Loud on junk (the NEMO_ANALYSIS_IMPL
    policy): a typo silently resolving to auto would change execution
    concurrency in exactly the dimension the operator was pinning."""
    v = os.environ.get("NEMO_SCHED", "auto").strip().lower()
    if v == "auto":
        return "auto"
    if v in ("1", "true", "yes", "on"):
        return "on"
    if v in ("0", "false", "no", "off"):
        return "off"
    raise ValueError(f"NEMO_SCHED={v!r} (expected auto, on, or off)")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None
    if val <= 0:
        raise ValueError(f"{name}={val} must be > 0")
    return val


@dataclass
class Job:
    """One schedulable bucket: identity for the cost model (verb, rows, V,
    E, work = rows x (V+E) — the same work unit as the PR-3 crossover) plus
    the execution callable.  ``execute(lane, reason, stolen)`` runs the
    bucket on the named lane and returns its result dict; the callable owns
    route recording and spans so records look identical to the serial path.
    ``pinned`` names the only lane allowed to run this job (a forced or
    platform route); ``reason`` is the route reason recorded when the job
    runs on its planned lane ("sched" for cost-model preferences)."""

    index: int
    verb: str
    rows: int
    v: int
    e: int
    work: int
    execute: Callable[[str, str, bool], dict]
    pinned: str | None = None
    reason: str = "sched"
    #: Who submitted the job — "pipeline" for the analysis drain, "serve"
    #: for the serving tier's cross-request merged kernel launches
    #: (nemo_tpu/serve/batch.py) — recorded per decision so telemetry can
    #: split a sidecar's own corpus work from its serving traffic.
    source: str = "pipeline"
    #: Set True BY the execute callable when the measured wall includes a
    #: one-off cost that must not feed the cost model — a jit compile
    #: (seconds) folded into a warm-execution EWMA (tens of ms) would price
    #: every later same-class bucket off the device lane for the whole
    #: session.  The scheduler still records the wall; it skips observe().
    wall_tainted: bool = False


class LaneModel:
    """Per-lane wall-clock predictor: wall ≈ fixed + unit x work, with a
    per-(verb, V, E) shape-class EWMA of measured per-row walls taking over
    once the lane has actually executed that class — measured walls beat
    any static model, and the shape class is what the jit cache keys on, so
    walls within a class are comparable.  ``hint(job)`` (optional) supplies
    a prediction between those two: consulted when the class is unmeasured,
    e.g. the PR-4 cost table's FLOPs estimate for a signature compiled in
    an earlier corpus."""

    def __init__(
        self,
        fixed_s: float,
        unit_s: float,
        alpha: float = 0.5,
        hint: Callable[[Job], float | None] | None = None,
    ) -> None:
        self.fixed_s = float(fixed_s)
        self.unit_s = float(unit_s)
        self.alpha = float(alpha)
        self.hint = hint
        #: (verb, v, e) -> EWMA seconds per row, measured by this process.
        self.per_row: dict[tuple[str, int, int], float] = {}

    def predict(self, job: Job) -> float:
        per_row = self.per_row.get((job.verb, job.v, job.e))
        if per_row is not None:
            return self.fixed_s + per_row * job.rows
        if self.hint is not None:
            h = self.hint(job)
            if h is not None:
                return self.fixed_s + float(h)
        return self.fixed_s + self.unit_s * job.work

    def observe(self, job: Job, wall_s: float) -> None:
        """Feed one measured execution back into the model (the feedback
        loop that corrects a mispredicted bucket for the whole session)."""
        variable = max(wall_s - self.fixed_s, 1e-9)
        per_row = variable / max(job.rows, 1)
        key = (job.verb, job.v, job.e)
        old = self.per_row.get(key)
        self.per_row[key] = (
            per_row if old is None else (1 - self.alpha) * old + self.alpha * per_row
        )
        unit = variable / max(job.work, 1)
        self.unit_s = (1 - self.alpha) * self.unit_s + self.alpha * unit


def default_models(
    host_work_budget: int | None = None,
    device_hint: Callable[[Job], float | None] | None = None,
) -> dict[str, LaneModel]:
    """Lane models seeded so an UNMEASURED scheduler reproduces the PR-3
    crossover: the host lane costs the sparse engine's measured ~1 us per
    work unit (BENCH sparse tier), and the device lane pays a fixed
    dispatch cost equal to the crossover budget's worth of host work —
    predictions then cross at exactly work ≈ NEMO_ANALYSIS_HOST_WORK, the
    measured break-even PR 3 shipped.  Feedback refines both from there."""
    host_unit = _env_float("NEMO_SCHED_HOST_UNIT", 1e-6)
    device_unit = _env_float("NEMO_SCHED_DEVICE_UNIT", 5e-8)
    budget = host_work_budget
    if budget is None:
        budget = int(os.environ.get("NEMO_ANALYSIS_HOST_WORK", "100000"))
    # fixed + unit_d*budget == unit_h*budget: the two lines intersect at
    # exactly the budget (a fixed of budget*unit_h alone would put the
    # break-even ~unit_d/unit_h above it).
    device_fixed = _env_float(
        "NEMO_SCHED_DEVICE_FIXED", budget * max(host_unit - device_unit, 1e-12)
    )
    return {
        "device": LaneModel(device_fixed, device_unit, hint=device_hint),
        "host": LaneModel(0.0, host_unit),
    }


#: Process-global lane models: measured walls persist across corpora in one
#: session (a long-lived sidecar keeps learning), like the jit cache.
_SESSION_MODELS: dict[str, LaneModel] | None = None
#: Process-global decision table exported to telemetry.json (bounded like
#: the metrics registry's series cap; drops are impossible — deque evicts).
_RECORDS: deque = deque(maxlen=512)
_RECORDS_LOCK = threading.Lock()


def session_models(
    host_work_budget: int | None = None,
    device_hint: Callable[[Job], float | None] | None = None,
) -> dict[str, LaneModel]:
    global _SESSION_MODELS
    if _SESSION_MODELS is None:
        _SESSION_MODELS = default_models(host_work_budget, device_hint)
    elif device_hint is not None and _SESSION_MODELS["device"].hint is None:
        _SESSION_MODELS["device"].hint = device_hint
    return _SESSION_MODELS


def reset_session_models() -> None:
    """Forget learned walls (tests, and operators bouncing a bad model)."""
    global _SESSION_MODELS
    _SESSION_MODELS = None
    with _RECORDS_LOCK:
        _RECORDS.clear()


def sched_snapshot() -> list[dict]:
    """The decision table as JSON-able records (newest last) — the
    telemetry.json `sched` section reads this."""
    with _RECORDS_LOCK:
        return [dict(r) for r in _RECORDS]


class HeterogeneousScheduler:
    """Two-lane work-stealing executor over a job list.

    ``run(jobs)`` drains the jobs on one worker thread per lane and returns
    results in job-index order.  Planned lanes come from the cost model
    (or the job's pin); an idle lane steals the next UNPINNED job from the
    other lane's tail (the far end — the victim lane keeps its head-of-line
    locality).  The first worker exception aborts both lanes and re-raises
    in the caller."""

    def __init__(self, models: dict[str, LaneModel] | None = None) -> None:
        self.models = models or session_models()
        self.steals = {lane: 0 for lane in LANES}
        self.dispatched = {lane: 0 for lane in LANES}

    def plan(self, job: Job) -> tuple[str, str, dict]:
        """(lane, reason, predictions) for one job."""
        preds = {lane: self.models[lane].predict(job) for lane in LANES}
        if job.pinned:
            return job.pinned, job.reason, preds
        lane = "device" if preds["device"] <= preds["host"] else "host"
        return lane, "sched", preds

    def run(self, jobs: list[Job], serial: bool = False) -> list[dict]:
        results: list[dict | None] = [None] * len(jobs)
        queues: dict[str, deque[Job]] = {lane: deque() for lane in LANES}
        plans: dict[int, tuple[str, str, dict]] = {}
        for job in jobs:
            lane, reason, preds = self.plan(job)
            plans[job.index] = (lane, reason, preds)
            queues[lane].append(job)
        obs.metrics.inc("analysis.sched.jobs", len(jobs))

        lock = threading.Lock()
        errors: list[BaseException] = []

        def run_one(job: Job, lane: str, stolen: bool) -> None:
            planned_lane, reason, preds = plans[job.index]
            if stolen:
                reason = "steal"
            t0 = time.perf_counter()
            res = job.execute(lane, reason, stolen)
            wall = time.perf_counter() - t0
            with lock:
                if not job.wall_tainted:
                    self.models[lane].observe(job, wall)
                self.dispatched[lane] += 1
                if stolen:
                    self.steals[lane] += 1
                results[job.index] = res
            obs.metrics.inc(f"analysis.sched.dispatch.{lane}")
            if stolen:
                obs.metrics.inc(f"analysis.sched.steal.{lane}")
            obs.metrics.observe(f"analysis.sched.wall_s.{lane}", wall)
            rec = {
                "index": job.index,
                "verb": job.verb,
                "rows": job.rows,
                "v": job.v,
                "e": job.e,
                "work": job.work,
                "lane": lane,
                "planned": planned_lane,
                "reason": reason,
                "source": job.source,
                "stolen": stolen,
                "pinned": job.pinned is not None,
                "tainted": job.wall_tainted,
                "predicted_s": {k: round(v, 6) for k, v in preds.items()},
                "wall_s": round(wall, 6),
            }
            with _RECORDS_LOCK:
                _RECORDS.append(rec)

        def take(lane: str) -> tuple[Job, bool] | None:
            """Pop the next job for `lane`: its own queue's head, else steal
            an unpinned job from the other lane's tail."""
            other = "host" if lane == "device" else "device"
            with lock:
                if queues[lane]:
                    return queues[lane].popleft(), False
                for i in range(len(queues[other]) - 1, -1, -1):
                    job = queues[other][i]
                    if job.pinned is None:
                        del queues[other][i]
                        return job, True
            return None

        # A job list pinned entirely to ONE lane has no concurrency to win
        # (stealing is forbidden, the other lane would idle-exit), so drain
        # it inline on the caller's thread — keeping kernel spans nested
        # under the caller's phase spans in the Perfetto view, exactly like
        # the serial loop.  The platform-routed CPU path (everything pinned
        # host) takes this branch.
        pinned_lanes = {job.pinned for job in jobs}
        if None not in pinned_lanes and len(pinned_lanes) == 1:
            serial = True
        if serial:
            # The NEMO_SCHED=off fallback (and the single-lane case): same
            # plans, same records, no threads — index order, planned lane.
            for job in jobs:
                run_one(job, plans[job.index][0], False)
            return results  # type: ignore[return-value]

        def worker(lane: str) -> None:
            while not errors:
                nxt = take(lane)
                if nxt is None:
                    return
                job, stolen = nxt
                try:
                    run_one(job, lane, stolen)
                except BaseException as ex:  # propagate to the caller
                    with lock:
                        errors.append(ex)
                    return

        with obs.span("analysis:sched", jobs=len(jobs)):
            threads = [
                threading.Thread(target=worker, args=(lane,), name=f"nemo-sched-{lane}")
                for lane in LANES
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errors:
            raise errors[0]
        missing = [j.index for j in jobs if results[j.index] is None]
        if missing:  # a lane died mid-drain without recording an exception
            raise RuntimeError(f"scheduler dropped jobs {missing}")
        return results  # type: ignore[return-value]
