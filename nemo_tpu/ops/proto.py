"""Success-prototype kernels.

Array form of the reference's extractProtos/missingFrom Cypher
(graphing/prototype.go:11-24, :143-147; corrected semantics per SURVEY.md §7):
per run, the rule tables on paths root-[1]->rule-[*1..]->rule from in-degree-0
goals of the simplified consequent graph — i.e. rules reachable from a root
that have a rule descendant or a reachable rule ancestor — gated on the run
having achieved the antecedent.  Cross-run intersection/union are AND/OR
reductions over the run axis (jnp.all/any; under a sharded mesh XLA lowers
them to all-reduces over ICI).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .adjacency import (
    closure,
    in_degree_any,
    reach_ge1,
    step_backward,
    step_forward,
    table_bitset,
    table_min,
)

DEPTH_INF = 1 << 20


def hop_depths(adj: jax.Array, start: jax.Array, max_depth: int) -> jax.Array:
    """Shortest hop distance [B,V] from start nodes, DEPTH_INF if unreachable.
    Bounded iteration (static trip count) per XLA's fixed-shape model."""
    depth = jnp.where(start, 0, DEPTH_INF)

    def body(_, d):
        stepped = jnp.min(jnp.where(adj, d[..., None], DEPTH_INF), axis=-2) + 1
        return jnp.minimum(d, stepped)

    return lax.fori_loop(0, max_depth, body, depth)


def _bfs_reach(start: jax.Array, adj: jax.Array, max_depth: int, backward: bool = False) -> jax.Array:
    """Set-BFS: nodes reachable from `start` in >= 1 hop (forward along
    edges, or backward with backward=True).  O(max_depth * V^2) — the
    giant-graph alternative to materializing the all-pairs closure."""
    hop = step_backward if backward else step_forward

    def body(_, carry):
        frontier, acc = carry
        frontier = hop(frontier, adj)
        return frontier, acc | frontier

    first = hop(start, adj)
    _, acc = lax.fori_loop(0, max(0, max_depth - 1), body, (first, first))
    return acc


def proto_rule_bits(
    adj: jax.Array,  # [B,V,V] simplified consequent adjacency
    is_goal: jax.Array,  # [B,V]
    alive: jax.Array,  # [B,V]
    table_id: jax.Array,  # [B,V]
    achieved_pre: jax.Array,  # [B] bool
    num_tables: int,
    max_depth: int,
    closure_impl: str = "auto",
    use_closure: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Returns (bits [B,T] bool, min_rule_depth [B,T] int32).

    use_closure=False swaps the all-pairs closure for three bounded
    set-BFS sweeps (O(max_depth * V^2) instead of O(V^3 log V)) — the
    giant-graph path, exact when max_depth >= the longest path."""
    a = adj & alive[..., None] & alive[..., None, :]
    root = is_goal & alive & ~in_degree_any(a)
    is_rule = ~is_goal & alive
    if use_closure:
        # Directed DAG closure: path lengths are bounded by the corpus
        # longest-path bound, so the squaring chain shortens with it.
        clo = closure(a, impl=closure_impl, max_len=max_depth)
        d1 = reach_ge1(a, clo)  # >=1-hop reachability
        reach = step_forward(root, d1) | jnp.zeros_like(root)  # nodes >=1 hop below a root
        rule_desc = step_backward(is_rule, d1)  # has a rule strictly below
        rule_anc = step_forward(is_rule & reach, d1)  # has a reachable rule strictly above
    else:
        reach = _bfs_reach(root, a, max_depth)
        rule_desc = _bfs_reach(is_rule, a, max_depth, backward=True)
        rule_anc = _bfs_reach(is_rule & reach, a, max_depth)
    qualify = is_rule & reach & (rule_desc | rule_anc) & achieved_pre[..., None]

    depth = hop_depths(a, root, max_depth)
    rule_depth = (depth + 1) // 2  # hops alternate goal/rule

    bits = table_bitset(qualify, table_id, num_tables)
    min_depth = table_min(rule_depth, qualify, table_id, num_tables, DEPTH_INF)
    return bits, min_depth


def all_rule_bits(
    is_goal: jax.Array, alive: jax.Array, table_id: jax.Array, num_tables: int
) -> jax.Array:
    """[B,T]: distinct rule tables present in each simplified graph
    (missingFrom's MATCH (r:Rule), prototype.go:143-147)."""
    return table_bitset(~is_goal & alive, table_id, num_tables)


def reduce_protos(bits: jax.Array, achieved: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(intersection [T], union [T]) over achieving runs.  Under a mesh with
    the run axis sharded, jnp.all/any lower to cross-device all-reduces."""
    masked = bits & achieved[..., None]
    inter = jnp.all(masked | ~achieved[..., None], axis=0) & jnp.any(achieved)
    union = jnp.any(masked, axis=0)
    return inter, union
