"""Batched sparse-CSR host analysis engine (ISSUE 3 tentpole).

Exact host-side mirror of the fused dense analysis step
(models/pipeline_model.py:analysis_step, with_diff=False) over a packed run
bucket: condition marking, clean-copy restriction + @next chain contraction,
and prototype bitsets — computed as flat edge-list scatters and CSR frontier
pushes in numpy, O(B * (V + E)) per sweep instead of the dense kernels'
O(B * V^2..V^3) matrix work.

This generalizes ``parallel/giant.py:giant_analysis_host`` (the B=1 giant
special case, measured ~34x faster than the sequential oracle where the
dense XLA:CPU kernels were 5-6x SLOWER, BENCH_r05 giant row) into the
engine the CPU-fallback tier routes EVERY dense bucket through
(backend/jax_backend.py:NEMO_ANALYSIS_IMPL).  The algorithmic position is
Beamer et al.'s direction-optimizing observation and the GraphBLAS
tradition: below a work threshold, sparse frontier push beats dense matrix
sweeps — and on a host CPU, provenance graphs (E ~ V, shallow DAGs) are
always below it.

Design notes:

  * Inputs are the SAME packed run buckets the dense dispatch consumes
    (graphs/packed.py [B,V]/[B,E] arrays) — no re-pack.  Edge lists are
    flattened once per (bucket, condition) into run-offset node indices
    (slot + row*V) by ``_CondCSR``; every verb reuses that shared prep, so
    the batch scatter construction is paid once per bucket, not per verb.
  * Edges never cross run boundaries (src and dst share a row), so one
    flat [B*V] node space batches all runs through every scatter/BFS with
    no per-run Python loop.
  * All reachability runs to FIX POINT (frontier push over a CSR), so no
    static depth bound is needed — exact wherever the bounded device
    kernels are exact (their trip counts are proven sufficient).
  * Component labels for the chain contraction: pointer doubling on the
    member-successor pointers when the bucket is verified linear (the same
    precondition as the device's comp_doubling fast path), else min-label
    relaxation to fix point over the undirected member edges (exact for
    any member structure — the host twin of the exact union-find labels
    the giant path ships to the device).
  * Output keys/shapes/values are bit-compatible with
    ``analysis_step(with_diff=False)``; the dense [B,V,V] clean
    adjacencies are materialized from the contracted edge lists (their
    downstream consumers — figure row-gathers — index them identically).

Reference semantics: markConditionHolds (pre-post-prov.go:220-243),
clean-copy + collapseNextChains (preprocessing.go:17-345), extractProtos
(prototype.go:11-24) — via the array forms in ops/condition.py,
ops/simplify.py, ops/proto.py, which remain the device implementations.
"""

from __future__ import annotations

import numpy as np

from nemo_tpu.graphs.packed import TYPE_ASYNC, TYPE_COLLAPSED, TYPE_NEXT
from nemo_tpu.ops.proto import DEPTH_INF

__all__ = [
    "build_csr",
    "bfs_any",
    "bfs_depths",
    "sparse_analysis_step",
    "synth_ext_host",
]


# --------------------------------------------------------------- CSR helpers


def build_csr(src: np.ndarray, dst: np.ndarray, n: int) -> tuple[np.ndarray, np.ndarray]:
    """Edge list -> (indptr [n+1], neighbors) CSR for frontier pushes.
    Duplicate edges are kept (every consumer here has 'any' semantics)."""
    order = np.argsort(src, kind="stable")
    nbr = dst[order]
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.bincount(src, minlength=n), out=indptr[1:])
    return indptr, nbr


def _expand(
    indptr: np.ndarray,
    nbr: np.ndarray,
    frontier: np.ndarray,
    return_counts: bool = False,
):
    """All CSR neighbors of the frontier nodes (with duplicates): the
    O(frontier edges) push step — total work across a whole BFS is
    O(E log E) (the log from frontier dedup in the callers), the property
    the dense per-iteration [B,V,V] sweeps lack.  return_counts=True also
    returns the per-frontier-node out-degrees, for callers that pair each
    expanded edge with its source (the Kahn relaxation in
    ops/diff.py:diff_masks_host)."""
    cnt = indptr[frontier + 1] - indptr[frontier]
    tot = int(cnt.sum())
    if tot == 0:
        empty = np.zeros(0, dtype=np.int64)
        return (empty, cnt) if return_counts else empty
    starts = np.repeat(indptr[frontier], cnt)
    offs = np.arange(tot, dtype=np.int64) - np.repeat(np.cumsum(cnt) - cnt, cnt)
    targets = nbr[starts + offs]
    return (targets, cnt) if return_counts else targets


def bfs_any(indptr: np.ndarray, nbr: np.ndarray, start: np.ndarray) -> np.ndarray:
    """Nodes reachable from `start` (flat bool [n]) in >= 1 hop; exact fix
    point (ops/proto.py:_bfs_reach semantics, unbounded).

    Each wave touches only the frontier's edges (np.unique dedups the next
    frontier) — no O(n) scratch per wave, so deep narrow graphs (a giant
    @next chain has depth ~ V) stay O(E log E) total instead of
    O(n * depth)."""
    n = len(indptr) - 1
    reach = np.zeros(n, dtype=bool)
    frontier = np.nonzero(start)[0]
    while frontier.size:
        targets = _expand(indptr, nbr, frontier)
        cand = targets[~reach[targets]] if targets.size else targets
        if not cand.size:
            break
        reach[cand] = True
        frontier = np.unique(cand)
    return reach


def bfs_depths(indptr: np.ndarray, nbr: np.ndarray, root: np.ndarray) -> np.ndarray:
    """Shortest hop distance from `root` (flat bool [n]); DEPTH_INF where
    unreachable (ops/proto.py:hop_depths semantics, exact).  Same
    frontier-local wave structure as bfs_any."""
    n = len(indptr) - 1
    depth = np.full(n, DEPTH_INF, dtype=np.int64)
    frontier = np.nonzero(root)[0]
    depth[frontier] = 0
    d = 0
    while frontier.size:
        d += 1
        targets = _expand(indptr, nbr, frontier)
        cand = targets[depth[targets] == DEPTH_INF] if targets.size else targets
        if not cand.size:
            break
        depth[cand] = d
        frontier = np.unique(cand)
    return depth


# ------------------------------------------------------------ shared prep


class _CondCSR:
    """Shared flat-scatter prep for ONE condition of a packed run bucket.

    Built once per (bucket, condition) and reused by every sparse verb —
    the "batch scatter construction" cost (mask-filtering the [B,E] edge
    planes and offsetting slots into the flat [B*V] node space) is the
    dominant fixed cost of the sparse route, so it is paid here exactly
    once.  Accepts anything exposing the 8 packed fields (PackedBatch,
    BatchArrays, a native corpus cond batch)."""

    __slots__ = (
        "b", "v", "n", "is_goal", "node_mask", "table", "type_id",
        "src", "dst", "goal",
    )

    def __init__(self, batch) -> None:
        self.is_goal = np.asarray(batch.is_goal, dtype=bool)
        self.node_mask = np.asarray(batch.node_mask, dtype=bool)
        self.table = np.asarray(batch.table_id, dtype=np.int64)
        self.type_id = np.asarray(batch.type_id, dtype=np.int64)
        self.b, self.v = self.is_goal.shape
        self.n = self.b * self.v
        em = np.asarray(batch.edge_mask, dtype=bool).ravel()
        src = np.asarray(batch.edge_src, dtype=np.int64).ravel()
        dst = np.asarray(batch.edge_dst, dtype=np.int64).ravel()
        e = src.shape[0] // self.b if self.b else 0
        base = np.repeat(np.arange(self.b, dtype=np.int64) * self.v, e)
        self.src = (base + src)[em]
        self.dst = (base + dst)[em]
        self.goal = self.is_goal & self.node_mask

    def scat_any(self, at: np.ndarray, vals: np.ndarray) -> np.ndarray:
        """[B,V] bool: any True `vals` scattered to flat node index `at`
        (bincount — orders of magnitude faster than ufunc.at at stress E)."""
        return (
            np.bincount(at[vals], minlength=self.n).reshape(self.b, self.v) > 0
        )


# ----------------------------------------------------------------- verbs


def _condition_holds(csr: _CondCSR, tid: int, num_tables: int) -> np.ndarray:
    """Batched mirror of ops/condition.py:mark_condition_holds."""
    goal, table = csr.goal, csr.table
    indeg = csr.scat_any(csr.dst, np.ones(len(csr.dst), dtype=bool))
    root = goal & (table == tid) & ~indeg
    rule = (
        csr.scat_any(csr.dst, root.ravel()[csr.src])
        & ~csr.is_goal
        & csr.node_mask
        & (table == tid)
    )
    trig = csr.scat_any(csr.dst, rule.ravel()[csr.src]) & csr.is_goal & csr.node_mask
    any_trig = trig.any(axis=-1, keepdims=True)
    # Per-run table bitset of the triggered goals (ops/adjacency.py:
    # table_bitset semantics: clip + table>=0 guard).
    tclip = np.clip(table, 0, num_tables - 1)
    tvalid = table >= 0
    rows = np.broadcast_to(np.arange(csr.b)[:, None], table.shape)
    sel = trig & tvalid
    trig_tables = (
        np.bincount(
            (rows[sel] * num_tables + tclip[sel]), minlength=csr.b * num_tables
        ).reshape(csr.b, num_tables)
        > 0
    )
    in_trig_table = np.take_along_axis(trig_tables, tclip, axis=-1) & tvalid
    return goal & any_trig & ((table == tid) | in_trig_table)


def _component_labels(
    csr: _CondCSR, member: np.ndarray, ks: np.ndarray, kd: np.ndarray, linear: bool
) -> np.ndarray:
    """Within-row component ids [B,V] of the member subgraph over the kept
    member edges; `v` for non-members.  Any consistent member-index-valued
    labeling works (ops/simplify.py:collapse_chains contract) — the labels
    only group, the representative is re-derived as the min head index.

    linear=True (bucket-VERIFIED, chains_linear_host / the C++ parse
    flags): pointer doubling along the unique member successor, O(V log V).
    Otherwise: min-label relaxation to fix point over the undirected
    member edges — exact for any member structure, the host twin of
    giant_plan's union-find."""
    b, v, n = csr.b, csr.v, csr.n
    member_f = member.ravel()
    m_edge = member_f[ks] & member_f[kd]
    ms, md = ks[m_edge], kd[m_edge]
    if linear:
        p = np.arange(n, dtype=np.int64)
        p[ms] = md  # <=1 member successor per member (verified linear)
        n_iters = max(1, (v - 1).bit_length())
        for _ in range(n_iters):
            p = p[p]
        lab = np.where(member_f, p % v, v)
        return lab.reshape(b, v)
    idx = np.tile(np.arange(v, dtype=np.int64), b)
    lab = np.where(member_f, idx, v)
    while ms.size:
        before = lab.copy()
        np.minimum.at(lab, md, lab[ms])
        np.minimum.at(lab, ms, lab[md])
        if np.array_equal(lab, before):
            break
    return lab.reshape(b, v)


def _simplify(csr: _CondCSR, linear: bool):
    """Batched mirror of clean_masks + collapse_chains.  Returns
    (adj_clean [B,V,V], alive_new [B,V], type_new [B,V],
    (new_src, new_dst) flat contracted edges for downstream sweeps)."""
    b, v, n = csr.b, csr.v, csr.n
    goal = csr.goal
    goal_f = goal.ravel()

    # --- clean-copy restriction (ops/simplify.py:clean_masks)
    has_in_goal = csr.scat_any(csr.dst, goal_f[csr.src])
    has_out_goal = csr.scat_any(csr.src, goal_f[csr.dst])
    is_rule = ~csr.is_goal & csr.node_mask
    alive = goal | (is_rule & has_in_goal & has_out_goal)
    alive_f = alive.ravel()
    keep = np.where(
        goal_f[csr.src],
        has_out_goal.ravel()[csr.dst],
        has_in_goal.ravel()[csr.src],
    )
    keep &= alive_f[csr.src] & alive_f[csr.dst]
    ks, kd = csr.src[keep], csr.dst[keep]

    # --- chain contraction (ops/simplify.py:collapse_chains)
    next_rule = is_rule & alive & (csr.type_id == TYPE_NEXT)
    nr_f = next_rule.ravel()
    in_from_next = csr.scat_any(kd, nr_f[ks])
    out_to_next = csr.scat_any(ks, nr_f[kd])
    member = next_rule | (goal & alive & in_from_next & out_to_next)
    member_f = member.ravel()

    lab = _component_labels(csr, member, ks, kd, linear)
    lab_c = np.clip(lab, 0, v - 1).ravel()

    in_from_member = csr.scat_any(kd, member_f[ks])
    out_to_member = csr.scat_any(ks, member_f[kd])
    head = next_rule & ~in_from_member
    tail = next_rule & ~out_to_member

    row_base = np.repeat(np.arange(b, dtype=np.int64) * v, v)
    idx_within = np.tile(np.arange(v, dtype=np.int64), b)
    comp_key = row_base + lab_c  # flat (row, component) slot

    rep_per_comp = np.full(n, v, dtype=np.int64)
    hm = head.ravel()  # head rules are members by construction
    np.minimum.at(rep_per_comp, comp_key[hm], idx_within[hm])
    n_rules_per_comp = np.bincount(comp_key[nr_f], minlength=n)
    collapsible_comp = (n_rules_per_comp >= 2) & (rep_per_comp < v)

    node_collapsible = member_f & collapsible_comp[comp_key]
    rep_of_node = np.where(node_collapsible, rep_per_comp[comp_key], idx_within)
    rep_flat = row_base + rep_of_node
    is_rep = node_collapsible & (idx_within == rep_of_node)
    dies = node_collapsible & ~is_rep
    ext_goal_f = goal_f & alive_f & ~member_f

    survive = ~node_collapsible[ks] & ~node_collapsible[kd]
    head_c = hm & node_collapsible
    tail_c = tail.ravel() & node_collapsible
    pred_sel = ext_goal_f[ks] & head_c[kd]
    succ_sel = tail_c[ks] & ext_goal_f[kd]
    new_src = np.concatenate([ks[survive], ks[pred_sel], rep_flat[ks[succ_sel]]])
    new_dst = np.concatenate([kd[survive], rep_flat[kd[pred_sel]], kd[succ_sel]])

    alive_new = alive & ~dies.reshape(b, v)
    type_new = np.where(is_rep.reshape(b, v), TYPE_COLLAPSED, csr.type_id).astype(
        np.int32
    )
    adj_new = np.zeros((b, v, v), dtype=bool)
    adj_new.reshape(n, v)[new_src, new_dst % v] = True
    return adj_new, alive_new, type_new, (new_src, new_dst)


def _proto(
    csr: _CondCSR,
    alive2: np.ndarray,
    edges: tuple[np.ndarray, np.ndarray],
    achieved: np.ndarray,
    num_tables: int,
):
    """Batched mirror of proto_rule_bits + all_rule_bits over the
    contracted consequent.  Returns (bits [B,T], min_depth [B,T] int32,
    present [B,T])."""
    b, v, n = csr.b, csr.v, csr.n
    alive_f = alive2.ravel()
    asrc, adst = edges
    ok = alive_f[asrc] & alive_f[adst]
    asrc, adst = asrc[ok], adst[ok]
    fwd = build_csr(asrc, adst, n)
    bwd = build_csr(adst, asrc, n)

    indeg = np.zeros(n, dtype=bool)
    indeg[adst] = True
    is_goal_f = csr.is_goal.ravel()
    root = is_goal_f & alive_f & ~indeg
    is_rule = ~is_goal_f & alive_f
    reach = bfs_any(*fwd, root)
    rule_desc = bfs_any(*bwd, is_rule)
    rule_anc = bfs_any(*fwd, is_rule & reach)
    achieved_f = np.repeat(np.asarray(achieved, dtype=bool), v)
    qualify = is_rule & reach & (rule_desc | rule_anc) & achieved_f

    depth = bfs_depths(*fwd, root)
    rule_depth = (depth + 1) // 2  # hops alternate goal/rule

    table_f = csr.table.ravel()
    rows = np.arange(n, dtype=np.int64) // v
    tclip = np.clip(table_f, 0, num_tables - 1)

    def table_bitset(mask: np.ndarray) -> np.ndarray:
        sel = mask & (table_f >= 0)
        return (
            np.bincount(
                rows[sel] * num_tables + tclip[sel], minlength=b * num_tables
            ).reshape(b, num_tables)
            > 0
        )

    bits = table_bitset(qualify)
    present = table_bitset(is_rule)
    min_depth = np.full(b * num_tables, DEPTH_INF, dtype=np.int64)
    qsel = qualify & (table_f >= 0)
    np.minimum.at(min_depth, rows[qsel] * num_tables + tclip[qsel], rule_depth[qsel])
    return bits, min_depth.reshape(b, num_tables).astype(np.int32), present


# ------------------------------------------------------------- synthesis


def synth_ext_host(batch, holds: np.ndarray, num_tables: int) -> np.ndarray:
    """Batched bincount-scatter twin of the ``synth_ext`` device kernel
    (ops/sparse_device.py:synth_ext_candidates; ISSUE 13): per-run
    extension-candidate table bitsets [B,T] — async rules adjacent to the
    antecedent's condition boundary (extensions.go:63-67), exactly the
    per-run PGraph walk of analysis/queries.py:extension_candidates, for
    every run of a packed bucket in one flat-space pass.

    ``batch`` is anything exposing the 8 packed fields (the _CondCSR
    contract); ``holds`` is the fused step's [B,V] pre_holds output.  The
    CPU-routing/lane-failover twin: the scheduler's host lane and the
    degraded (breaker-open) mode run this bit-identically."""
    csr = _CondCSR(batch)
    b, v, n = csr.b, csr.v, csr.n
    holds_f = np.asarray(holds, dtype=bool).ravel()
    goal_f = csr.goal.ravel()
    g_hold = goal_f & holds_f
    g_nohold = goal_f & ~holds_f
    nongoal = (~csr.is_goal & csr.node_mask).ravel()

    has_nongoal_child = csr.scat_any(csr.src, nongoal[csr.dst]).ravel()
    qual_child = g_nohold & has_nongoal_child
    holding_parent = csr.scat_any(csr.dst, g_hold[csr.src]).ravel()
    nonhold_parent = csr.scat_any(csr.dst, g_nohold[csr.src]).ravel()
    has_qual_child = csr.scat_any(csr.src, qual_child[csr.dst]).ravel()

    cand = (
        nongoal
        & (csr.type_id.ravel() == TYPE_ASYNC)
        & ((holding_parent & has_qual_child) | nonhold_parent)
    )
    table_f = csr.table.ravel()
    rows = np.arange(n, dtype=np.int64) // v
    tclip = np.clip(table_f, 0, num_tables - 1)
    sel = cand & (table_f >= 0)
    return (
        np.bincount(
            rows[sel] * num_tables + tclip[sel], minlength=b * num_tables
        ).reshape(b, num_tables)
        > 0
    )


# ------------------------------------------------------------- fused step


def sparse_analysis_step(
    pre,
    post,
    v: int,
    pre_tid: int,
    post_tid: int,
    num_tables: int,
    comp_linear: bool = False,
    with_diff: bool = False,
    **_compat,
) -> dict[str, np.ndarray]:
    """Exact sparse host mirror of analysis_step(with_diff=False) for one
    packed (pre, post) run bucket: same output keys, shapes, and values.

    `pre`/`post` are anything exposing the 8 packed fields at [B,V]/[B,E]
    (PackedBatch straight from the bucketizer — no re-pack — or
    BatchArrays; device arrays are pulled host-side).  `comp_linear` is the
    bucket's verified linearity flag, selecting the pointer-doubling
    component labels (same precondition as the device fast path).  The
    remaining analysis_step statics (num_labels, max_depth, closure_impl,
    pack_out) are accepted and ignored: sweeps run to fix point, nothing is
    compiled, and nothing crosses a transfer boundary.

    The differential tail is NOT mirrored here — the production backend
    diffs in its own good-run-anchored pass (ops/diff.py:diff_masks_host is
    the sparse side of that crossover) — so with_diff must stay False.
    """
    if with_diff:
        raise ValueError(
            "sparse_analysis_step has no differential tail (with_diff=True); "
            "the backend diffs via its own routed pass (ops/diff.py)"
        )
    out: dict[str, np.ndarray] = {}
    post_ctx = None
    for name, batch, tid in (("pre", pre, pre_tid), ("post", post, post_tid)):
        csr = _CondCSR(batch)
        if csr.v != v:
            raise ValueError(f"batch V={csr.v} != static v={v}")
        out[f"{name}_holds"] = _condition_holds(csr, tid, num_tables)
        adj_new, alive2, type_new, coll_edges = _simplify(csr, comp_linear)
        out[f"{name}_adj_clean"] = adj_new
        out[f"{name}_alive"] = alive2
        out[f"{name}_type"] = type_new
        if name == "post":
            post_ctx = (csr, alive2, coll_edges)
    achieved = out["pre_holds"].any(axis=-1)
    out["achieved_pre"] = achieved

    csr_p, alive2_p, coll_p = post_ctx
    bits, min_depth, present = _proto(csr_p, alive2_p, coll_p, achieved, num_tables)
    out["proto_bits"] = bits
    out["proto_min_depth"] = min_depth
    out["proto_present"] = present
    # Cross-run reductions (ops/proto.py:reduce_protos semantics).
    masked = bits & achieved[:, None]
    out["proto_inter"] = np.all(masked | ~achieved[:, None], axis=0) & achieved.any()
    out["proto_union"] = np.any(masked, axis=0)
    return out
