"""Differential-provenance kernels.

Array form of the reference's CreateNaiveDiffProv
(graphing/differential-provenance.go:18-243; semantics per backend/base.py):
the diff graph keeps nodes/edges of the good run's consequent provenance that
lie on a path between two goals whose labels are absent from the failed run
(endpoint-filtered: forward-reachable from an ok goal AND backward-reachable
to one); the missing-event frontier is the terminal rule of the longest
root->leaf paths plus all its goal children.  The failed-run label set enters
as a label-vocab bitset; everything vmaps over the failed-run axis against a
single shared good graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .adjacency import closure, in_degree_any, out_degree_any

NEG_INF = -(1 << 20)


def longest_depths(adj: jax.Array, start: jax.Array, max_depth: int) -> jax.Array:
    """Longest path length (in edges) from start nodes; NEG_INF if unreachable.
    Bounded max-plus iteration; exact when max_depth >= graph depth."""
    d = jnp.where(start, 0, NEG_INF)

    def body(_, dist):
        stepped = jnp.max(jnp.where(adj, dist[..., None], NEG_INF), axis=-2) + 1
        return jnp.maximum(dist, stepped)

    return lax.fori_loop(0, max_depth, body, d)


def diff_masks(
    adj_good: jax.Array,  # [V,V] good run's raw consequent adjacency
    is_goal: jax.Array,  # [V]
    node_mask: jax.Array,  # [V]
    label_id: jax.Array,  # [V]
    fail_bits: jax.Array,  # [B,L] one bitset per failed run
    max_depth: int,
    closure_impl: str = "auto",
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (node_keep [B,V], edge_keep [B,V,V], frontier_rule [B,V],
    missing_goal [B,V])."""
    num_labels = fail_bits.shape[-1]
    lid = jnp.clip(label_id, 0, num_labels - 1)
    # [V,V], shared across failed runs; directed DAG closure, so the corpus
    # longest-path bound caps the squaring chain.
    clo = closure(adj_good, impl=closure_impl, max_len=max_depth)

    def per_run(bits: jax.Array):
        in_failed = bits[lid] & (label_id >= 0)
        ok = is_goal & node_mask & ~in_failed
        fwd = (clo & ok[:, None]).any(axis=0)  # >=0 hops from an ok goal
        bwd = (clo & ok[None, :]).any(axis=1)  # >=0 hops to an ok goal
        node_keep = fwd & bwd & node_mask
        edge_keep = adj_good & fwd[:, None] & bwd[None, :]

        root = is_goal & node_keep & ~in_degree_any(edge_keep)
        leaf = is_goal & node_keep & ~out_degree_any(edge_keep)
        dist = longest_depths(edge_keep, root, max_depth)
        leaf_dist = jnp.where(leaf & (dist >= 1), dist, NEG_INF)
        max_len = jnp.max(leaf_dist)
        frontier_rule = (
            ~is_goal
            & node_keep
            & (dist + 1 == max_len)
            & (edge_keep & (leaf & (dist == max_len))[None, :]).any(axis=1)
        )
        missing_goal = is_goal & node_keep & (edge_keep & frontier_rule[:, None]).any(axis=0)
        return node_keep, edge_keep, frontier_rule, missing_goal

    return jax.vmap(per_run)(fail_bits)


def diff_masks_host(
    edges,  # [E,2] int (src,dst) of the good run's consequent provenance
    n_nodes: int,
    is_goal,  # [V] bool (numpy)
    label_id,  # [V] int
    fail_bits,  # [B,L] bool
):
    """Sparse host-side diff_masks for ONE giant good run.

    Semantics identical to diff_masks, but O(B * (V + E)) on the packed
    edge list instead of dense [V,V] device arrays: a 10k-node good graph's
    dense closure is V^3-prohibitive, while its real edge count is ~V (the
    giant-graph path, backend/jax_backend.py NEMO_GIANT_V dispatch).

    Returns (node_keep [B,V], edge_keep_mask [B,E] — a mask over `edges`
    rather than a dense [V,V] — frontier_rule [B,V], missing_goal [B,V]).
    """
    import numpy as np

    v = n_nodes
    e = len(edges)
    src = edges[:, 0] if e else np.zeros(0, dtype=np.int64)
    dst = edges[:, 1] if e else np.zeros(0, dtype=np.int64)
    b = fail_bits.shape[0]
    num_labels = fail_bits.shape[-1]
    lid = np.clip(label_id, 0, num_labels - 1)

    out_adj: list[list[int]] = [[] for _ in range(v)]
    in_adj: list[list[int]] = [[] for _ in range(v)]
    for s, d in zip(src.tolist(), dst.tolist()):
        out_adj[s].append(d)
        in_adj[d].append(s)

    def reach(start_mask, adj):
        seen = start_mask.copy()
        stack = list(np.nonzero(start_mask)[0])
        while stack:
            u = stack.pop()
            for w in adj[u]:
                if not seen[w]:
                    seen[w] = True
                    stack.append(w)
        return seen

    node_keep = np.zeros((b, v), dtype=bool)
    edge_keep = np.zeros((b, e), dtype=bool)
    frontier_rule = np.zeros((b, v), dtype=bool)
    missing_goal = np.zeros((b, v), dtype=bool)
    for j in range(b):
        in_failed = fail_bits[j][lid] & (label_id >= 0)
        ok = is_goal & ~in_failed
        fwd = reach(ok, out_adj)  # >=0 hops from an ok goal
        bwd = reach(ok, in_adj)  # >=0 hops to an ok goal
        keep = fwd & bwd
        node_keep[j] = keep
        ek = keep[src] & keep[dst] if e else edge_keep[j]
        edge_keep[j] = ek

        indeg = np.zeros(v, dtype=np.int64)
        outdeg = np.zeros(v, dtype=np.int64)
        np.add.at(indeg, dst[ek], 1)
        np.add.at(outdeg, src[ek], 1)
        root = is_goal & keep & (indeg == 0)
        leaf = is_goal & keep & (outdeg == 0)

        # Longest path from roots by topological relaxation over kept edges.
        dist = np.where(root, 0, NEG_INF)
        kout: list[list[int]] = [[] for _ in range(v)]
        for s, d in zip(src[ek].tolist(), dst[ek].tolist()):
            kout[s].append(d)
        deg = indeg.copy()
        stack = [u for u in range(v) if keep[u] and deg[u] == 0]
        while stack:
            u = stack.pop()
            du = dist[u]
            for w in kout[u]:
                if du + 1 > dist[w]:
                    dist[w] = du + 1
                deg[w] -= 1
                if deg[w] == 0:
                    stack.append(w)

        leaf_dist = np.where(leaf & (dist >= 1), dist, NEG_INF)
        max_len = leaf_dist.max() if v else NEG_INF
        deepest_leaf = leaf & (dist == max_len)
        to_deepest = np.zeros(v, dtype=bool)
        np.logical_or.at(to_deepest, src[ek], deepest_leaf[dst[ek]])
        frontier_rule[j] = ~is_goal & keep & (dist + 1 == max_len) & to_deepest
        from_frontier = np.zeros(v, dtype=bool)
        np.logical_or.at(from_frontier, dst[ek], frontier_rule[j][src[ek]])
        missing_goal[j] = is_goal & keep & from_frontier
    return node_keep, edge_keep, frontier_rule, missing_goal
