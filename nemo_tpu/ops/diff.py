"""Differential-provenance kernels.

Array form of the reference's CreateNaiveDiffProv
(graphing/differential-provenance.go:18-243; semantics per backend/base.py):
the diff graph keeps nodes/edges of the good run's consequent provenance that
lie on a path between two goals whose labels are absent from the failed run
(endpoint-filtered: forward-reachable from an ok goal AND backward-reachable
to one); the missing-event frontier is the terminal rule of the longest
root->leaf paths plus all its goal children.  The failed-run label set enters
as a label-vocab bitset; everything vmaps over the failed-run axis against a
single shared good graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .adjacency import closure, in_degree_any, out_degree_any

NEG_INF = -(1 << 20)


def longest_depths(adj: jax.Array, start: jax.Array, max_depth: int) -> jax.Array:
    """Longest path length (in edges) from start nodes; NEG_INF if unreachable.
    Bounded max-plus iteration; exact when max_depth >= graph depth."""
    d = jnp.where(start, 0, NEG_INF)

    def body(_, dist):
        stepped = jnp.max(jnp.where(adj, dist[..., None], NEG_INF), axis=-2) + 1
        return jnp.maximum(dist, stepped)

    return lax.fori_loop(0, max_depth, body, d)


def diff_masks(
    adj_good: jax.Array,  # [V,V] good run's raw consequent adjacency
    is_goal: jax.Array,  # [V]
    node_mask: jax.Array,  # [V]
    label_id: jax.Array,  # [V]
    fail_bits: jax.Array,  # [B,L] one bitset per failed run
    max_depth: int,
    closure_impl: str = "auto",
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (node_keep [B,V], edge_keep [B,V,V], frontier_rule [B,V],
    missing_goal [B,V])."""
    num_labels = fail_bits.shape[-1]
    lid = jnp.clip(label_id, 0, num_labels - 1)
    # [V,V], shared across failed runs; directed DAG closure, so the corpus
    # longest-path bound caps the squaring chain.
    clo = closure(adj_good, impl=closure_impl, max_len=max_depth)

    def per_run(bits: jax.Array):
        in_failed = bits[lid] & (label_id >= 0)
        ok = is_goal & node_mask & ~in_failed
        fwd = (clo & ok[:, None]).any(axis=0)  # >=0 hops from an ok goal
        bwd = (clo & ok[None, :]).any(axis=1)  # >=0 hops to an ok goal
        node_keep = fwd & bwd & node_mask
        edge_keep = adj_good & fwd[:, None] & bwd[None, :]

        root = is_goal & node_keep & ~in_degree_any(edge_keep)
        leaf = is_goal & node_keep & ~out_degree_any(edge_keep)
        dist = longest_depths(edge_keep, root, max_depth)
        leaf_dist = jnp.where(leaf & (dist >= 1), dist, NEG_INF)
        max_len = jnp.max(leaf_dist)
        frontier_rule = (
            ~is_goal
            & node_keep
            & (dist + 1 == max_len)
            & (edge_keep & (leaf & (dist == max_len))[None, :]).any(axis=1)
        )
        missing_goal = is_goal & node_keep & (edge_keep & frontier_rule[:, None]).any(axis=0)
        return node_keep, edge_keep, frontier_rule, missing_goal

    return jax.vmap(per_run)(fail_bits)


def diff_masks_host(
    edges,  # [E,2] int (src,dst) of the good run's consequent provenance
    n_nodes: int,
    is_goal,  # [V] bool (numpy)
    label_id,  # [V] int
    fail_bits,  # [B,L] bool
):
    """Sparse host-side diff_masks for the good run vs B failed runs.

    Semantics identical to diff_masks, but O(B * (V + E)) on the packed
    edge list instead of dense [V,V] device arrays: a 10k-node good graph's
    dense closure is V^3-prohibitive, while its real edge count is ~V (the
    giant-graph path, backend/jax_backend.py NEMO_GIANT_V dispatch).

    Implementation rides the batched sparse-CSR engine's shared prep
    (ops/sparse_host.py, ISSUE 3): the good graph's edge list is offset
    into one flat [B*V] node space (edges never cross run copies) so ALL
    failed runs batch through each CSR frontier push and one vectorized
    Kahn longest-path wave — no per-run Python adjacency lists or BFS
    stacks (the pre-r6 shape, measured ~5x slower at the stress failed-run
    counts).

    Returns (node_keep [B,V], edge_keep_mask [B,E] — a mask over `edges`
    rather than a dense [V,V] — frontier_rule [B,V], missing_goal [B,V]).
    """
    import numpy as np

    from nemo_tpu.ops.sparse_host import _expand, bfs_any, build_csr

    v = n_nodes
    e = len(edges)
    b = fail_bits.shape[0]
    n = b * v
    num_labels = fail_bits.shape[-1]
    label_id = np.asarray(label_id)
    is_goal = np.asarray(is_goal, dtype=bool)
    lid = np.clip(label_id, 0, num_labels - 1)

    # Per-run ok-goal masks, then everything batches in the flat space.
    in_failed = np.asarray(fail_bits, dtype=bool)[:, lid] & (label_id >= 0)[None, :]
    okf = (is_goal[None, :] & ~in_failed).ravel()
    goal_f = np.tile(is_goal, b)

    if e:
        src = np.asarray(edges[:, 0], dtype=np.int64)
        dst = np.asarray(edges[:, 1], dtype=np.int64)
        base = np.repeat(np.arange(b, dtype=np.int64) * v, e)
        fsrc = base + np.tile(src, b)
        fdst = base + np.tile(dst, b)
    else:
        fsrc = fdst = np.zeros(0, dtype=np.int64)
    fwd = build_csr(fsrc, fdst, n)
    bwd = build_csr(fdst, fsrc, n)

    # >=0-hop reach from / to an ok goal (start | >=1-hop push).
    keepf = (okf | bfs_any(*fwd, okf)) & (okf | bfs_any(*bwd, okf))
    ekf = keepf[fsrc] & keepf[fdst]
    ks, kd = fsrc[ekf], fdst[ekf]

    indeg = np.bincount(kd, minlength=n)
    outdeg = np.bincount(ks, minlength=n)
    root = goal_f & keepf & (indeg == 0)
    leaf = goal_f & keepf & (outdeg == 0)

    # Longest path from roots: vectorized Kahn waves over the kept edges.
    # A node enters the frontier only when its kept in-degree hits zero, so
    # its dist is final when its out-edges relax — the exact topological
    # relaxation the per-run loop performed.
    kptr, knbr = build_csr(ks, kd, n)
    dist = np.where(root, 0, NEG_INF)
    deg = indeg.copy()
    frontier = np.nonzero(keepf & (deg == 0))[0]
    while frontier.size:
        targets, cnt = _expand(kptr, knbr, frontier, return_counts=True)
        if not targets.size:
            break
        np.maximum.at(dist, targets, np.repeat(dist[frontier], cnt) + 1)
        np.subtract.at(deg, targets, 1)
        uniq = np.unique(targets)
        frontier = uniq[deg[uniq] == 0]

    leaf_dist = np.where(leaf & (dist >= 1), dist, NEG_INF).reshape(b, v)
    max_len = leaf_dist.max(axis=1) if v else np.full(b, NEG_INF)
    deepest_leaf = (leaf.reshape(b, v) & (dist.reshape(b, v) == max_len[:, None])).ravel()
    to_deepest = np.bincount(ks[deepest_leaf[kd]], minlength=n) > 0
    frontier_rule = (
        ~goal_f & keepf & (dist + 1 == np.repeat(max_len, v)) & to_deepest
    )
    from_frontier = np.bincount(kd[frontier_rule[ks]], minlength=n) > 0
    missing_goal = goal_f & keepf & from_frontier
    return (
        keepf.reshape(b, v),
        ekf.reshape(b, e),
        frontier_rule.reshape(b, v),
        missing_goal.reshape(b, v),
    )
