"""Differential-provenance kernels.

Array form of the reference's CreateNaiveDiffProv
(graphing/differential-provenance.go:18-243; semantics per backend/base.py):
the diff graph keeps nodes/edges of the good run's consequent provenance that
lie on a path between two goals whose labels are absent from the failed run
(endpoint-filtered: forward-reachable from an ok goal AND backward-reachable
to one); the missing-event frontier is the terminal rule of the longest
root->leaf paths plus all its goal children.  The failed-run label set enters
as a label-vocab bitset; everything vmaps over the failed-run axis against a
single shared good graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .adjacency import closure, in_degree_any, out_degree_any

NEG_INF = -(1 << 20)


def longest_depths(adj: jax.Array, start: jax.Array, max_depth: int) -> jax.Array:
    """Longest path length (in edges) from start nodes; NEG_INF if unreachable.
    Bounded max-plus iteration; exact when max_depth >= graph depth."""
    d = jnp.where(start, 0, NEG_INF)

    def body(_, dist):
        stepped = jnp.max(jnp.where(adj, dist[..., None], NEG_INF), axis=-2) + 1
        return jnp.maximum(dist, stepped)

    return lax.fori_loop(0, max_depth, body, d)


def diff_masks(
    adj_good: jax.Array,  # [V,V] good run's raw consequent adjacency
    is_goal: jax.Array,  # [V]
    node_mask: jax.Array,  # [V]
    label_id: jax.Array,  # [V]
    fail_bits: jax.Array,  # [B,L] one bitset per failed run
    max_depth: int,
    closure_impl: str = "auto",
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (node_keep [B,V], edge_keep [B,V,V], frontier_rule [B,V],
    missing_goal [B,V])."""
    num_labels = fail_bits.shape[-1]
    lid = jnp.clip(label_id, 0, num_labels - 1)
    clo = closure(adj_good, impl=closure_impl)  # [V,V], shared across failed runs

    def per_run(bits: jax.Array):
        in_failed = bits[lid] & (label_id >= 0)
        ok = is_goal & node_mask & ~in_failed
        fwd = (clo & ok[:, None]).any(axis=0)  # >=0 hops from an ok goal
        bwd = (clo & ok[None, :]).any(axis=1)  # >=0 hops to an ok goal
        node_keep = fwd & bwd & node_mask
        edge_keep = adj_good & fwd[:, None] & bwd[None, :]

        root = is_goal & node_keep & ~in_degree_any(edge_keep)
        leaf = is_goal & node_keep & ~out_degree_any(edge_keep)
        dist = longest_depths(edge_keep, root, max_depth)
        leaf_dist = jnp.where(leaf & (dist >= 1), dist, NEG_INF)
        max_len = jnp.max(leaf_dist)
        frontier_rule = (
            ~is_goal
            & node_keep
            & (dist + 1 == max_len)
            & (edge_keep & (leaf & (dist == max_len))[None, :]).any(axis=1)
        )
        missing_goal = is_goal & node_keep & (edge_keep & frontier_rule[:, None]).any(axis=0)
        return node_keep, edge_keep, frontier_rule, missing_goal

    return jax.vmap(per_run)(fail_bits)
