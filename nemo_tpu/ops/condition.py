"""Condition-holds marking kernel.

Array form of the reference's markConditionHolds Cypher
(graphing/pre-post-prov.go:218-244): find the root goal of the condition's
table (no incoming edge), its child rules of the same table, and THEIR child
goals g; if any such g exists, set condition_holds on every goal whose table
is the condition's or any g's.  Two masked BFS hops plus a table scatter,
vmapped over the run batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .adjacency import in_degree_any, step_forward, table_bitset


def mark_condition_holds(
    adj: jax.Array,  # [B,V,V] bool
    is_goal: jax.Array,  # [B,V] bool
    table_id: jax.Array,  # [B,V] int32
    node_mask: jax.Array,  # [B,V] bool
    cond_tid: int,
    num_tables: int,
) -> jax.Array:
    """Returns cond_holds [B,V] bool."""
    root = is_goal & node_mask & (table_id == cond_tid) & ~in_degree_any(adj)
    rule = step_forward(root, adj) & ~is_goal & node_mask & (table_id == cond_tid)
    trig = step_forward(rule, adj) & is_goal & node_mask
    any_trig = trig.any(axis=-1, keepdims=True)
    trig_tables = table_bitset(trig, table_id, num_tables)  # [B,T]
    tid = jnp.clip(table_id, 0, num_tables - 1)
    in_trig_table = jnp.take_along_axis(trig_tables, tid, axis=-1) & (table_id >= 0)
    return is_goal & node_mask & any_trig & ((table_id == cond_tid) | in_trig_table)
