"""Device-native sparse-CSR analysis kernels (ISSUE 10 tentpole).

The dense device tier (ops/adjacency.py + models/pipeline_model.py) computes
every verb on a materialized [B,V,V] one-hot adjacency — O(B*V^2) memory and
O(B*V^2..V^3) matrix work per bucket.  That is the right trade at case-study
sizes (V <= a few hundred, MXU-friendly), but it caps V: giant-V families
fall off the accelerator entirely (``parallel/giant.py`` host fallback) and
the mostly-empty graphs Molly emits pay dense bandwidth for nnz ~ V edges.

This module is the DEVICE twin of ``ops/sparse_host.py`` (the batched CSR
host engine PR 3 proved bit-exact): the same frontier algorithm — condition
marking, clean/collapse, component labels, prototype fix-point pushes, and
the diff verb — expressed as jittable gather/scatter waves over the packed
[B,E] edge planes:

  * the padded edge lists ARE the stable-signature sparse layout: [B,V]
    node planes + [B,E] (src, dst, mask) edge planes, both nnz-derived
    power-of-two buckets (graphs/packed.py) — no ragged shapes, one
    compiled program per bucket class, run-axis shardable with
    ``NamedSharding(P("run"))`` exactly like the dense batch arrays;
  * every frontier wave is one gather (``take_along_axis`` by edge source)
    plus one scatter (``.at[...].max/min/add``, the jnp form of
    ``jax.ops.segment_sum``) — O(B*E) per wave, never O(B*V^2);
  * reachability runs to FIX POINT (``lax.while_loop`` on a changed
    predicate), so no static depth bound is needed — exact wherever the
    bounded dense kernels are exact, including arbitrary (zigzag) member
    structures where the dense path needs all-pairs closures;
  * the clean/collapsed adjacency leaves the program as a CONTRACTED EDGE
    LIST ([B,E] src/dst/mask), not a dense [B,V,V] plane; the host-side
    :class:`CsrAdjRows` view densifies exactly the rows figure
    materialization touches (the diff verb's sparse-host precedent).

Memory per bucket drops from O(B*V^2) to O(B*(V+E)) — the ~V^2/nnz-fold
watermark reduction ROADMAP item 4 names — which is what lets giant-V runs
stay on the device instead of falling back to the host.

Wave implementations: ``NEMO_SPARSE_WAVE_IMPL=auto|xla|pallas``.  auto
resolves to xla (scatter waves; GSPMD can partition them, so it is the only
legal choice under a sharded jit — the closure-impl precedent).  ``pallas``
runs the scatter-heavy reach waves as a fused VMEM kernel
(ops/pallas_kernels.py:edge_wave_pallas): n steps per HBM round-trip, the
one-hot compare formulation instead of a Mosaic scatter (which does not
lower).  Exercised in interpreter mode by tests/test_sparse_device.py;
bit-identical by construction (monotone reach makes extra fused steps
harmless).

Reference semantics: markConditionHolds (pre-post-prov.go:220-243),
clean-copy + collapseNextChains (preprocessing.go:17-345), extractProtos
(prototype.go:11-24), CreateNaiveDiffProv (differential-provenance.go) —
via the array forms in ops/condition.py, ops/simplify.py, ops/proto.py,
ops/diff.py, which remain the dense implementations.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from nemo_tpu.graphs.packed import TYPE_ASYNC, TYPE_COLLAPSED, TYPE_NEXT
from nemo_tpu.ops.proto import DEPTH_INF

__all__ = [
    "CsrAdjRows",
    "resolve_wave_impl",
    "sparse_device_step",
    "diff_masks_sparse_device",
    "synth_ext_candidates",
]


def resolve_wave_impl(impl: str | None = None) -> str:
    """Resolve the frontier-wave implementation: None/"auto" ->
    NEMO_SPARSE_WAVE_IMPL, defaulting to xla.  Mirrors
    ops/adjacency.py:resolve_closure_impl — xla is the default because the
    scatter waves are GSPMD-partitionable (a Mosaic pallas_call is not) and
    measured fine at production shapes; the fused pallas wave stays the
    explicit opt-in for directly-attached TPUs where the per-wave HBM
    round-trips dominate."""
    impl = impl or os.environ.get("NEMO_SPARSE_WAVE_IMPL", "auto")
    if impl == "auto":
        impl = "xla"
    if impl not in ("xla", "pallas"):
        raise ValueError(
            f"unknown sparse wave impl {impl!r} (expected auto, xla, or pallas)"
        )
    return impl


#: VMEM budget gate for the pallas wave (the kernel holds two [E,V] one-hot
#: planes per graph): buckets past this e*v product silently use the xla
#: waves even under NEMO_SPARSE_WAVE_IMPL=pallas — the fused kernel is a
#: small-bucket optimization, not a giant-V path.
_PALLAS_WAVE_MAX_EV = 1 << 22

#: Fused wave steps per pallas HBM round-trip (monotone reach makes extra
#: steps harmless, so the only cost of a high count is wasted MXU work on
#: converged rows).
_PALLAS_WAVE_STEPS = 4


# ------------------------------------------------------------ wave helpers


def _gather(vals: jax.Array, idx: jax.Array) -> jax.Array:
    """vals [B,V] gathered by idx [B,E] -> [B,E]."""
    return jnp.take_along_axis(vals, idx, axis=1)


def _scat_any(vals_e: jax.Array, dst: jax.Array, v: int) -> jax.Array:
    """[B,E] bool scattered (any) to [B,V] by dst — the segment-sum push."""
    b = dst.shape[0]
    bi = jnp.arange(b)[:, None]
    return jnp.zeros((b, v), dtype=bool).at[bi, dst].max(vals_e)


def _push_any(state: jax.Array, src, dst, mask, v: int) -> jax.Array:
    """One frontier wave: nodes with an in-edge from `state` (>=1 hop)."""
    return _scat_any(_gather(state, src) & mask, dst, v)


def _reach_any(start, src, dst, mask, v: int, wave_impl: str, interpret: bool):
    """Nodes reachable from `start` in >= 1 hop; exact fix point
    (ops/sparse_host.py:bfs_any semantics)."""
    if wave_impl == "pallas":
        from nemo_tpu.ops.pallas_kernels import edge_wave_pallas

        def body(carry):
            acc, _ = carry
            nxt = edge_wave_pallas(
                acc | start, src, dst, mask,
                n_steps=_PALLAS_WAVE_STEPS, interpret=interpret,
            )
            # The kernel propagates >=0 hops from its input set; >=1-hop
            # reach is the propagation minus the start-only seed, which the
            # union with acc (already >=1-hop) keeps exact: any node the
            # kernel reaches beyond the seed took >=1 edge.
            nxt = acc | _push_any(nxt, src, dst, mask, v)
            return nxt, (nxt != acc).any()

        acc0 = _push_any(start, src, dst, mask, v)
        acc, _ = lax.while_loop(lambda c: c[1], body, (acc0, jnp.array(True)))
        return acc

    def body(carry):
        acc, _ = carry
        nxt = acc | _push_any(acc | start, src, dst, mask, v)
        return nxt, (nxt != acc).any()

    acc0 = _push_any(start, src, dst, mask, v)
    acc, _ = lax.while_loop(lambda c: c[1], body, (acc0, jnp.array(True)))
    return acc


def _bfs_depths(root, src, dst, mask, v: int) -> jax.Array:
    """Shortest hop distance [B,V] from root; DEPTH_INF where unreachable
    (ops/sparse_host.py:bfs_depths semantics).  Scatter-min relaxation to
    fix point — updates only decrease, so convergence is exact."""
    b = src.shape[0]
    bi = jnp.arange(b)[:, None]
    depth0 = jnp.where(root, 0, DEPTH_INF).astype(jnp.int32)

    def body(carry):
        depth, _ = carry
        stepped = _gather(depth, src) + 1
        stepped = jnp.where(mask, stepped, DEPTH_INF)
        nd = jnp.full((b, v), DEPTH_INF, dtype=jnp.int32).at[bi, dst].min(stepped)
        new = jnp.minimum(depth, nd)
        return new, (new != depth).any()

    depth, _ = lax.while_loop(lambda c: c[1], body, (depth0, jnp.array(True)))
    return depth


def _table_any(mask_bv, table, num_tables: int) -> jax.Array:
    """[B,V] node mask -> [B,T] per-table any-bitset (table -1 = padding);
    the scatter twin of ops/adjacency.py:table_bitset."""
    b = table.shape[0]
    bi = jnp.arange(b)[:, None]
    tclip = jnp.clip(table, 0, num_tables - 1)
    sel = mask_bv & (table >= 0)
    return jnp.zeros((b, num_tables), dtype=bool).at[bi, tclip].max(sel)


def _table_min(values, mask_bv, table, num_tables: int, fill: int) -> jax.Array:
    """[B,V] int values -> [B,T] per-table min over masked nodes (else
    fill); the scatter twin of ops/adjacency.py:table_min."""
    b = table.shape[0]
    bi = jnp.arange(b)[:, None]
    tclip = jnp.clip(table, 0, num_tables - 1)
    sel = mask_bv & (table >= 0)
    vals = jnp.where(sel, values, fill).astype(jnp.int32)
    return jnp.full((b, num_tables), fill, dtype=jnp.int32).at[bi, tclip].min(vals)


# ----------------------------------------------------------------- verbs


def _condition_holds(ba, tid, num_tables: int, v: int) -> jax.Array:
    """Sparse mirror of ops/condition.py:mark_condition_holds over the raw
    [B,E] edge planes."""
    goal = ba.is_goal & ba.node_mask
    table = ba.table_id
    indeg = _scat_any(ba.edge_mask, ba.edge_dst, v)
    root = goal & (table == tid) & ~indeg
    rule = (
        _push_any(root, ba.edge_src, ba.edge_dst, ba.edge_mask, v)
        & ~ba.is_goal
        & ba.node_mask
        & (table == tid)
    )
    trig = (
        _push_any(rule, ba.edge_src, ba.edge_dst, ba.edge_mask, v)
        & ba.is_goal
        & ba.node_mask
    )
    any_trig = trig.any(axis=-1, keepdims=True)
    trig_tables = _table_any(trig, table, num_tables)
    tclip = jnp.clip(table, 0, num_tables - 1)
    in_trig_table = jnp.take_along_axis(trig_tables, tclip, axis=-1) & (table >= 0)
    return goal & any_trig & ((table == tid) | in_trig_table)


def _component_labels(member, me, src, dst, v: int, comp_linear: bool):
    """Within-row component ids [B,V] of the member subgraph over the kept
    member edges (`me` [B,E]); `v` for non-members.  Any consistent
    member-index-valued labeling works (ops/simplify.py:collapse_chains
    contract) — only the grouping matters, the representative is re-derived
    as the min head index.

    comp_linear=True (bucket-VERIFIED): pointer doubling along the unique
    member successor, O(V log V) — the dense fast path's twin.  Otherwise:
    min-label relaxation to FIX POINT over the undirected member edges
    (lax.while_loop) — exact for any member structure, including the zigzag
    components whose undirected diameter the directed depth does not bound
    (the case the dense path needs all-pairs closures for)."""
    b = src.shape[0]
    bi = jnp.arange(b)[:, None]
    idx = jnp.broadcast_to(jnp.arange(v), (b, v))
    if comp_linear:
        # <=1 member successor per member (verified linear): a scatter-max
        # against the -1 sentinel recovers it exactly.
        succ = jnp.full((b, v), -1, dtype=jnp.int32).at[bi, src].max(
            jnp.where(me, dst, -1).astype(jnp.int32)
        )
        p = jnp.where(succ >= 0, succ, idx)
        n_iters = max(1, (v - 1).bit_length())
        for _ in range(n_iters):
            p = jnp.take_along_axis(p, p, axis=-1)
        return jnp.where(member, p, v)

    lab0 = jnp.where(member, idx, v).astype(jnp.int32)

    def body(carry):
        lab, _ = carry
        ls = jnp.where(me, _gather(lab, src), v)
        ld = jnp.where(me, _gather(lab, dst), v)
        new = lab.at[bi, dst].min(ls)
        new = new.at[bi, src].min(ld)
        return new, (new != lab).any()

    lab, _ = lax.while_loop(lambda c: c[1], body, (lab0, jnp.array(True)))
    return jnp.where(member, lab, v)


def _simplify(ba, v: int, comp_linear: bool):
    """Sparse mirror of clean_masks + collapse_chains.  Returns
    (new_src, new_dst, new_mask  — the CONTRACTED edge list [B,E] —
    alive_new [B,V], type_new [B,V] int32)."""
    b = ba.is_goal.shape[0]
    src, dst, em = ba.edge_src, ba.edge_dst, ba.edge_mask
    goal = ba.is_goal & ba.node_mask

    # --- clean-copy restriction (ops/simplify.py:clean_masks)
    has_in_goal = _scat_any(_gather(goal, src) & em, dst, v)
    has_out_goal = _scat_any(_gather(goal, dst) & em, src, v)
    is_rule = ~ba.is_goal & ba.node_mask
    alive = goal | (is_rule & has_in_goal & has_out_goal)
    keep = em & jnp.where(
        _gather(goal, src), _gather(has_out_goal, dst), _gather(has_in_goal, src)
    )
    keep &= _gather(alive, src) & _gather(alive, dst)

    # --- chain contraction (ops/simplify.py:collapse_chains)
    next_rule = is_rule & alive & (ba.type_id == TYPE_NEXT)
    in_from_next = _scat_any(_gather(next_rule, src) & keep, dst, v)
    out_to_next = _scat_any(_gather(next_rule, dst) & keep, src, v)
    member = next_rule | (goal & alive & in_from_next & out_to_next)
    me = keep & _gather(member, src) & _gather(member, dst)

    lab = _component_labels(member, me, src, dst, v, comp_linear)
    lab_c = jnp.clip(lab, 0, v - 1)

    in_from_member = _scat_any(_gather(member, src) & keep, dst, v)
    out_to_member = _scat_any(_gather(member, dst) & keep, src, v)
    head = next_rule & ~in_from_member
    tail = next_rule & ~out_to_member

    bi = jnp.arange(b)[:, None]
    idx = jnp.broadcast_to(jnp.arange(v), (b, v))
    # head rules are members by construction, so `head` alone selects the
    # component heads whose min index becomes the representative.
    rep_per_comp = (
        jnp.full((b, v), v, dtype=jnp.int32)
        .at[bi, lab_c]
        .min(jnp.where(head, idx, v).astype(jnp.int32))
    )
    n_rules_per_comp = (
        jnp.zeros((b, v), dtype=jnp.int32).at[bi, lab_c].add(next_rule.astype(jnp.int32))
    )
    collapsible_comp = (n_rules_per_comp >= 2) & (rep_per_comp < v)

    node_collapsible = member & jnp.take_along_axis(collapsible_comp, lab_c, axis=-1)
    rep_of_node = jnp.where(
        node_collapsible, jnp.take_along_axis(rep_per_comp, lab_c, axis=-1), idx
    )
    is_rep = node_collapsible & (idx == rep_of_node)
    dies = node_collapsible & ~is_rep
    ext_goal = goal & alive & ~member

    # In-place edge contraction: the three kept groups of the host engine
    # (survivors, ext-goal->head preds remapped to the rep column, tail->
    # ext-goal succs remapped to the rep row) are mutually exclusive per
    # edge, so the contracted graph is a REMAP of the kept edge list — no
    # concatenation, no ragged shapes, same [B,E] signature.
    nc_s = _gather(node_collapsible, src)
    nc_d = _gather(node_collapsible, dst)
    survive = ~nc_s & ~nc_d
    pred_sel = _gather(ext_goal, src) & _gather(head & node_collapsible, dst)
    succ_sel = _gather(tail & node_collapsible, src) & _gather(ext_goal, dst)
    new_mask = keep & (survive | pred_sel | succ_sel)
    new_src = jnp.where(succ_sel, _gather(rep_of_node, src), src)
    new_dst = jnp.where(pred_sel, _gather(rep_of_node, dst), dst)

    alive_new = alive & ~dies
    type_new = jnp.where(is_rep, TYPE_COLLAPSED, ba.type_id).astype(jnp.int32)
    return new_src, new_dst, new_mask, alive_new, type_new


def _proto(
    ba,
    alive2,
    edges,  # (new_src, new_dst, new_mask) contracted consequent edges
    achieved,
    num_tables: int,
    v: int,
    wave_impl: str,
    interpret: bool,
):
    """Sparse mirror of proto_rule_bits + all_rule_bits over the contracted
    consequent.  Returns (bits [B,T], min_depth [B,T] int32, present)."""
    asrc, adst, amask = edges
    pm = amask & _gather(alive2, asrc) & _gather(alive2, adst)

    indeg = _scat_any(pm, adst, v)
    root = ba.is_goal & alive2 & ~indeg
    is_rule = ~ba.is_goal & alive2
    reach = _reach_any(root, asrc, adst, pm, v, wave_impl, interpret)
    rule_desc = _reach_any(is_rule, adst, asrc, pm, v, wave_impl, interpret)
    rule_anc = _reach_any(is_rule & reach, asrc, adst, pm, v, wave_impl, interpret)
    qualify = is_rule & reach & (rule_desc | rule_anc) & achieved[:, None]

    depth = _bfs_depths(root, asrc, adst, pm, v)
    rule_depth = (depth + 1) // 2  # hops alternate goal/rule

    bits = _table_any(qualify, ba.table_id, num_tables)
    present = _table_any(is_rule, ba.table_id, num_tables)
    min_depth = _table_min(rule_depth, qualify, ba.table_id, num_tables, DEPTH_INF)
    return bits, min_depth, present


# ------------------------------------------------------------- fused step


@partial(
    jax.jit,
    static_argnames=("v", "num_tables", "comp_linear", "pack_out", "wave_impl", "interpret"),
)
def _sparse_step_jit(
    pre,
    post,
    pre_tid,
    post_tid,
    v: int,
    num_tables: int,
    comp_linear: bool,
    pack_out: bool,
    wave_impl: str,
    interpret: bool,
) -> dict[str, jnp.ndarray]:
    from nemo_tpu.models.pipeline_model import (
        SUMMARY_PACK_LAYOUT,
        fold_packed_summary,
        widen_batch,
    )

    pre = widen_batch(pre)
    post = widen_batch(post)
    out: dict = {}
    post_ctx = None
    for name, ba, tid in (("pre", pre, pre_tid), ("post", post, post_tid)):
        out[f"{name}_holds"] = _condition_holds(ba, tid, num_tables, v)
        new_src, new_dst, new_mask, alive2, type2 = _simplify(ba, v, comp_linear)
        out[f"{name}_clean_src"] = new_src.astype(jnp.int32)
        out[f"{name}_clean_dst"] = new_dst.astype(jnp.int32)
        out[f"{name}_clean_mask"] = new_mask
        out[f"{name}_alive"] = alive2
        out[f"{name}_type"] = type2
        if name == "post":
            post_ctx = (ba, alive2, (new_src, new_dst, new_mask))
    achieved = out["pre_holds"].any(axis=-1)
    out["achieved_pre"] = achieved

    ba_p, alive2_p, edges_p = post_ctx
    bits, min_depth, present = _proto(
        ba_p, alive2_p, edges_p, achieved, num_tables, v, wave_impl, interpret
    )
    out["proto_bits"] = bits
    out["proto_min_depth"] = min_depth
    out["proto_present"] = present
    # Cross-run reductions (ops/proto.py:reduce_protos semantics); under a
    # run-sharded mesh these lower to all-reduces exactly like the dense
    # step's.
    masked = bits & achieved[:, None]
    out["proto_inter"] = jnp.all(masked | ~achieved[:, None], axis=0) & jnp.any(achieved)
    out["proto_union"] = jnp.any(masked, axis=0)
    if pack_out:
        fold_packed_summary(out, SUMMARY_PACK_LAYOUT)
    return out


def sparse_device_step(
    pre,
    post,
    v: int,
    pre_tid: int,
    post_tid: int,
    num_tables: int,
    comp_linear: bool = False,
    pack_out: bool = False,
    wave_impl: str | None = None,
) -> dict[str, jnp.ndarray]:
    """Sparse-device mirror of analysis_step(with_diff=False) for one packed
    (pre, post) run bucket: same summary keys/shapes/values, with the dense
    [B,V,V] clean adjacencies replaced by contracted edge lists
    (``{cond}_clean_src/dst/mask`` [B,E] — densify per row via
    :class:`CsrAdjRows`).

    `pre`/`post` are BatchArrays (or anything field-compatible); integer
    planes may arrive narrowed (widen_batch casts them back in-program).
    ``wave_impl`` resolves pre-jit (the closure_impl precedent) so changing
    NEMO_SPARSE_WAVE_IMPL between calls takes effect; pallas silently falls
    back to the xla waves past the kernel's VMEM budget (its docstring)."""
    wave = resolve_wave_impl(wave_impl)
    e = int(pre.edge_src.shape[-1])
    if wave == "pallas" and e * v > _PALLAS_WAVE_MAX_EV:
        wave = "xla"
    return _sparse_step_jit(
        pre,
        post,
        pre_tid,
        post_tid,
        v=v,
        num_tables=num_tables,
        comp_linear=bool(comp_linear),
        pack_out=bool(pack_out),
        wave_impl=wave,
        interpret=jax.default_backend() != "tpu",
    )


# ------------------------------------------------------------------- diff


@partial(jax.jit, static_argnames=("v",))
def _sparse_diff_jit(src, dst, em, is_goal, node_mask, label_id, fail_bits, v: int):
    """Sparse-device mirror of ops/diff.py:diff_masks over the good run's
    padded edge list: one shared [E] edge list, every failed run batched
    through the same waves.  Returns (node_keep [B,V], edge_keep [B,E] —
    a mask over the edge list, the diff_masks_host convention —
    frontier_rule [B,V], missing_goal [B,V])."""
    from nemo_tpu.ops.diff import NEG_INF

    b = fail_bits.shape[0]
    e = src.shape[0]
    num_labels = fail_bits.shape[-1]
    lid = jnp.clip(label_id, 0, num_labels - 1)
    src_b = jnp.broadcast_to(src.astype(jnp.int32), (b, e))
    dst_b = jnp.broadcast_to(dst.astype(jnp.int32), (b, e))
    em_b = jnp.broadcast_to(em.astype(bool), (b, e))

    in_failed = jnp.take_along_axis(fail_bits, lid[None, :].repeat(b, 0), axis=1) & (
        label_id >= 0
    )
    ok = (is_goal & node_mask)[None, :] & ~in_failed

    # >=0-hop reach from / to an ok goal (start | >=1-hop push).
    fwd = ok | _reach_any(ok, src_b, dst_b, em_b, v, "xla", False)
    bwd = ok | _reach_any(ok, dst_b, src_b, em_b, v, "xla", False)
    node_keep = fwd & bwd & node_mask[None, :]
    edge_keep = em_b & _gather(node_keep, src_b) & _gather(node_keep, dst_b)

    goal_b = is_goal[None, :] & node_keep
    indeg = _scat_any(edge_keep, dst_b, v)
    outdeg = _scat_any(edge_keep, src_b, v)
    root = goal_b & ~indeg
    leaf = goal_b & ~outdeg

    # Longest path from roots over the kept edges: max-plus relaxation to
    # fix point — exact on the DAGs provenance graphs are (the dense
    # kernel's bounded iteration and the host Kahn wave compute the same).
    # The trip count is CAPPED at v: a simple path has < v edges, so the
    # cap never cuts a DAG's fix point short, and on a (schema-valid but
    # cyclic) adversarial input — where max-plus relaxation alone would
    # keep incrementing forever — the loop terminates like its dense
    # (max_depth-bounded fori) and host (cycle-safe Kahn) twins instead of
    # wedging the dispatch.
    bi = jnp.arange(b)[:, None]
    dist0 = jnp.where(root, 0, NEG_INF).astype(jnp.int32)

    def body(carry):
        dist, _, it = carry
        stepped = jnp.where(edge_keep, _gather(dist, src_b) + 1, NEG_INF)
        nd = jnp.full((b, v), NEG_INF, dtype=jnp.int32).at[bi, dst_b].max(stepped)
        new = jnp.maximum(dist, nd)
        return new, (new != dist).any(), it + 1

    dist, _, _ = lax.while_loop(
        lambda c: c[1] & (c[2] < v),
        body,
        (dist0, jnp.array(True), jnp.asarray(0, dtype=jnp.int32)),
    )

    leaf_dist = jnp.where(leaf & (dist >= 1), dist, NEG_INF)
    max_len = jnp.max(leaf_dist, axis=-1, keepdims=True)
    deepest_leaf = leaf & (dist == max_len)
    to_deepest = _scat_any(edge_keep & _gather(deepest_leaf, dst_b), src_b, v)
    frontier_rule = ~is_goal[None, :] & node_keep & (dist + 1 == max_len) & to_deepest
    missing_goal = goal_b & _scat_any(edge_keep & _gather(frontier_rule, src_b), dst_b, v)
    return node_keep, edge_keep, frontier_rule, missing_goal


def diff_masks_sparse_device(
    edge_src,  # [E] int (padded edge list of the good run's consequent)
    edge_dst,  # [E]
    edge_mask,  # [E] bool
    is_goal,  # [V] bool
    node_mask,  # [V] bool
    label_id,  # [V] int
    fail_bits,  # [B,L] bool
    v: int,
):
    """Device twin of ops/diff.py:diff_masks_host: same semantics and return
    convention (edge_keep is a mask over the edge list, not dense [B,V,V]),
    computed as batched gather/scatter waves — O(B*(V+E)) device memory
    instead of the dense path's [B,V,V] edge_keep planes."""
    return _sparse_diff_jit(
        jnp.asarray(edge_src),
        jnp.asarray(edge_dst),
        jnp.asarray(edge_mask),
        jnp.asarray(is_goal),
        jnp.asarray(node_mask),
        jnp.asarray(label_id),
        jnp.asarray(fail_bits),
        v=v,
    )


# ------------------------------------------------------------- synthesis


@partial(jax.jit, static_argnames=("v", "num_tables"))
def _synth_ext_jit(
    src, dst, em, is_goal, node_mask, type_id, table_id, holds, v: int, num_tables: int
):
    """Batched extension-candidate extraction (ISSUE 13): the async rules
    adjacent to the antecedent's condition boundary
    (analysis/queries.py:extension_candidates, extensions.go:63-67), for
    EVERY run of a packed bucket in one program.  A candidate rule r is
    non-goal, type async, and satisfies either

      cond_a: some holding goal parent AND some child goal that does not
              hold and itself has a non-goal child; or
      cond_b: some non-holding goal parent.

    Each clause is one single-step gather/scatter over the [B,E] edge
    planes — no fix points, so the whole verb is a handful of
    segment-sum pushes — and the per-run candidate TABLE bitset [B,T]
    folds via the shared table scatter.  Exactly the per-run PGraph
    walk's semantics (the parity battery pins all three routes)."""
    goal = is_goal & node_mask
    g_hold = goal & holds
    g_nohold = goal & ~holds
    nongoal = ~is_goal & node_mask
    # c has a non-goal child (the inner qualifier of cond_a).
    has_nongoal_child = _scat_any(_gather(nongoal, dst) & em, src, v)
    qual_child = g_nohold & has_nongoal_child
    holding_parent = _scat_any(_gather(g_hold, src) & em, dst, v)
    nonhold_parent = _scat_any(_gather(g_nohold, src) & em, dst, v)
    has_qual_child = _scat_any(_gather(qual_child, dst) & em, src, v)
    cand = (
        nongoal
        & (type_id == TYPE_ASYNC)
        & ((holding_parent & has_qual_child) | nonhold_parent)
    )
    return _table_any(cand, table_id, num_tables)


def synth_ext_candidates(
    edge_src,  # [B,E] int
    edge_dst,  # [B,E]
    edge_mask,  # [B,E] bool
    is_goal,  # [B,V] bool
    node_mask,  # [B,V] bool
    type_id,  # [B,V] int
    table_id,  # [B,V] int
    holds,  # [B,V] bool (the fused step's {cond}_holds output)
    v: int,
    num_tables: int,
):
    """Device twin of ops/sparse_host.py:synth_ext_host: per-run
    extension-candidate table bitsets [B,T] as batched gather/scatter
    pushes over the packed edge planes.  Served by the ``synth_ext``
    executor verb (backend/jax_backend.py) so RemoteExecutor/sidecar run
    it over the Kernel RPC unchanged; row-independent, so the serving
    tier's continuous batcher may merge compatible dispatches."""
    return _synth_ext_jit(
        jnp.asarray(edge_src).astype(jnp.int32),
        jnp.asarray(edge_dst).astype(jnp.int32),
        jnp.asarray(edge_mask, dtype=bool),
        jnp.asarray(is_goal, dtype=bool),
        jnp.asarray(node_mask, dtype=bool),
        jnp.asarray(type_id).astype(jnp.int32),
        jnp.asarray(table_id).astype(jnp.int32),
        jnp.asarray(holds, dtype=bool),
        v=v,
        num_tables=num_tables,
    )


# ------------------------------------------------------- host-side views


class CsrAdjRows:
    """Lazy dense view over a contracted [B,E] edge list: row-indexing
    densifies exactly the rows the caller touches (figure materialization,
    backend/jax_backend.py:_prefetch_clean_rows) into [V,V] / [k,V,V]
    boolean planes — the whole-bucket dense [B,V,V] plane is never built,
    which is the sparse route's memory contract.

    Supports the two access patterns the backend uses: ``adj[row]`` (int)
    and ``adj[rows]`` (index array), both returning numpy."""

    __slots__ = ("src", "dst", "mask", "v", "shape")

    def __init__(self, src, dst, mask, v: int) -> None:
        self.src = np.asarray(src)
        self.dst = np.asarray(dst)
        self.mask = np.asarray(mask, dtype=bool)
        self.v = int(v)
        self.shape = (self.src.shape[0], self.v, self.v)

    def _densify(self, rows: np.ndarray) -> np.ndarray:
        k = len(rows)
        out = np.zeros((k, self.v, self.v), dtype=bool)
        for j, r in enumerate(rows):
            m = self.mask[r]
            out[j, self.src[r][m], self.dst[r][m]] = True
        return out

    def __getitem__(self, key):
        if np.ndim(key) == 0:
            return self._densify(np.asarray([key]).ravel())[0]
        return self._densify(np.asarray(key).ravel())

    def __len__(self) -> int:
        return self.shape[0]

    def __array__(self, dtype=None):
        dense = self._densify(np.arange(self.shape[0]))
        return dense if dtype is None else dense.astype(dtype)
