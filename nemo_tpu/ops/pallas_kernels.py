"""Pallas TPU kernels for the hot graph ops.

The hottest op in the fused analysis step is transitive closure
(ops/adjacency.py:closure): log2(V) squarings of [B,V,V] boolean matrices.
Under plain XLA each squaring is a separate MXU matmul whose input and output
round-trip HBM — 2·log2(V)·B·V² of traffic for a compute-light 0/1 matmul
chain, i.e. HBM-bandwidth-bound at the corpus sizes the stress bench runs
(V 32–128, B in the thousands).  The Pallas kernel fuses the whole squaring
chain: each grid instance DMAs a block of graphs into VMEM once, runs every
squaring on the MXU from VMEM, and writes the finished closure back once —
HBM traffic drops to read+write of the block regardless of log2(V).

Boolean exactness: entries are 0/1 (exact in bf16 and int8), products
accumulate in f32 (bf16 path, exact up to V ≤ 2^24) or int32 (int8 path),
thresholded at > 0 each squaring — sums of 0/1 products are non-negative
integers, so the threshold is exact in both.

Used via ops.adjacency.closure's impl dispatch (NEMO_CLOSURE_IMPL =
auto|xla|pallas; auto picks pallas on TPU backends).  CPU tests run the same
kernel in interpreter mode (tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _closure_kernel(adj_ref, out_ref, *, n_steps: int, block_b: int, v: int, compute_dtype):
    acc_dtype = jnp.int32 if compute_dtype == jnp.int8 else jnp.float32
    row = jax.lax.broadcasted_iota(jnp.int32, (v, v), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (v, v), 1)
    eye = (row == col).astype(compute_dtype)
    # Static unroll over the graphs of this block: Mosaic's dot lowering is
    # 2-D, and block_b is small (VMEM-bounded), so unrolling beats a loop.
    for i in range(block_b):
        r = jnp.maximum(adj_ref[i], eye)
        for _ in range(n_steps):
            p = jnp.dot(r, r, preferred_element_type=acc_dtype)
            r = (p > 0).astype(compute_dtype)
        out_ref[i] = r


def default_block_b(v: int, itemsize: int = 2) -> int:
    """Graphs per grid instance, sized so ~3 live [block_b,V,V] buffers stay
    well under VMEM (~16 MB/core); int8 compute fits twice as many as bf16."""
    scale = max(1, 2 // itemsize)
    if v <= 128:
        return 8 * scale
    if v <= 256:
        return 4 * scale
    if v <= 512:
        return 2 * scale
    return 1 * scale


def _compute_dtype():
    """bf16 by default; NEMO_PALLAS_DTYPE=int8 switches the squaring chain to
    int8xint8->int32 MXU matmuls (half the VMEM, higher int throughput on
    TPUs that support it).  Both are exact for 0/1 entries."""
    import os

    name = os.environ.get("NEMO_PALLAS_DTYPE", "bfloat16")
    if name in ("int8", "i8"):
        return jnp.int8
    if name in ("bfloat16", "bf16"):
        return jnp.bfloat16
    raise ValueError(
        f"unknown NEMO_PALLAS_DTYPE {name!r} (expected bfloat16/bf16 or int8/i8)"
    )


def closure_pallas(
    adj: jax.Array,
    block_b: int | None = None,
    interpret: bool = False,
    compute_dtype=None,
) -> jax.Array:
    """Reflexive-transitive closure of [B,V,V] (or [V,V]) boolean adjacency,
    fused squaring chain in VMEM.  Bit-identical to adjacency.closure."""
    squeeze = adj.ndim == 2
    if squeeze:
        adj = adj[None]
    dt = compute_dtype or _compute_dtype()
    b, v, _ = adj.shape
    n_steps = max(1, (v - 1).bit_length())
    bb = min(block_b or default_block_b(v, jnp.dtype(dt).itemsize), b)
    x = adj.astype(dt)
    pad = (-b) % bb
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_closure_kernel, n_steps=n_steps, block_b=bb, v=v, compute_dtype=dt),
        out_shape=jax.ShapeDtypeStruct(x.shape, dt),
        grid=(x.shape[0] // bb,),
        in_specs=[pl.BlockSpec((bb, v, v), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((bb, v, v), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(x)
    res = out[:b] > 0
    return res[0] if squeeze else res
