"""Pallas TPU kernels for the hot graph ops.

The hottest op in the fused analysis step is transitive closure
(ops/adjacency.py:closure): log2(V) squarings of [B,V,V] boolean matrices.
Under plain XLA each squaring is a separate MXU matmul whose input and output
round-trip HBM — 2·log2(V)·B·V² of traffic for a compute-light 0/1 matmul
chain, i.e. HBM-bandwidth-bound at the corpus sizes the stress bench runs
(V 32–128, B in the thousands).  The Pallas kernel fuses the whole squaring
chain: each grid instance DMAs a block of graphs into VMEM once, runs every
squaring on the MXU from VMEM, and writes the finished closure back once —
HBM traffic drops to read+write of the block regardless of log2(V).

Boolean exactness: entries are 0/1 (exact in bf16), products accumulate in
f32 (exact up to V ≤ 2^24), thresholded at 0.5 each squaring.

Used via ops.adjacency.closure's impl dispatch (NEMO_CLOSURE_IMPL =
auto|xla|pallas; auto picks pallas on TPU backends).  CPU tests run the same
kernel in interpreter mode (tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _closure_kernel(adj_ref, out_ref, *, n_steps: int, block_b: int, v: int):
    row = jax.lax.broadcasted_iota(jnp.int32, (v, v), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (v, v), 1)
    eye = (row == col).astype(jnp.bfloat16)
    # Static unroll over the graphs of this block: Mosaic's dot lowering is
    # 2-D, and block_b is small (VMEM-bounded), so unrolling beats a loop.
    for i in range(block_b):
        r = jnp.maximum(adj_ref[i], eye)
        for _ in range(n_steps):
            p = jnp.dot(r, r, preferred_element_type=jnp.float32)
            r = (p > 0.5).astype(jnp.bfloat16)
        out_ref[i] = r


def default_block_b(v: int) -> int:
    """Graphs per grid instance, sized so ~3 live [block_b,V,V] bf16 buffers
    stay well under VMEM (~16 MB/core)."""
    if v <= 128:
        return 8
    if v <= 256:
        return 4
    if v <= 512:
        return 2
    return 1


def closure_pallas(
    adj: jax.Array, block_b: int | None = None, interpret: bool = False
) -> jax.Array:
    """Reflexive-transitive closure of [B,V,V] (or [V,V]) boolean adjacency,
    fused squaring chain in VMEM.  Bit-identical to adjacency.closure."""
    squeeze = adj.ndim == 2
    if squeeze:
        adj = adj[None]
    b, v, _ = adj.shape
    n_steps = max(1, (v - 1).bit_length())
    bb = min(block_b or default_block_b(v), b)
    x = adj.astype(jnp.bfloat16)
    pad = (-b) % bb
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_closure_kernel, n_steps=n_steps, block_b=bb, v=v),
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.bfloat16),
        grid=(x.shape[0] // bb,),
        in_specs=[pl.BlockSpec((bb, v, v), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((bb, v, v), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(x)
    res = out[:b] > 0.5
    return res[0] if squeeze else res
