"""Pallas TPU kernels for the hot graph ops.

The hottest op in the fused analysis step is transitive closure
(ops/adjacency.py:closure): log2(V) squarings of [B,V,V] boolean matrices.
Under plain XLA each squaring is a separate MXU matmul whose input and output
round-trip HBM — 2·log2(V)·B·V² of traffic for a compute-light 0/1 matmul
chain, i.e. HBM-bandwidth-bound at the corpus sizes the stress bench runs
(V 32–128, B in the thousands).  The Pallas kernel fuses the whole squaring
chain: each grid instance DMAs a block of graphs into VMEM once, runs every
squaring on the MXU from VMEM, and writes the finished closure back once —
HBM traffic drops to read+write of the block regardless of log2(V).

Boolean exactness: entries are 0/1 (exact in bf16 and int8), products
accumulate in f32 (bf16 path, exact up to V ≤ 2^24) or int32 (int8 path),
thresholded at > 0 each squaring — sums of 0/1 products are non-negative
integers, so the threshold is exact in both.

Used via ops.adjacency.closure's impl dispatch (NEMO_CLOSURE_IMPL =
auto|xla|pallas).  NOTE: auto resolves to XLA — the v5e sweep in
resolve_closure_impl's docstring shows XLA winning or tying at every
production shape even against this kernel's block-diagonal packing; the
closure is too small to be HBM-bound there, so the fused chain's thesis
does not pay.  The kernel remains the explicit opt-in fused option and the
reference for Mosaic patterns (block-diag MXU packing, VMEM scratch
assembly).  CPU tests run the same kernel in interpreter mode
(tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _closure_kernel(
    adj_ref, out_ref, scratch_ref=None, *, n_steps: int, block_b: int, v: int, g: int, compute_dtype
):
    """Fused squaring chain with block-diagonal MXU packing: g = 128//v
    graphs share one (g*v, g*v) matrix, so each jnp.dot drives a full
    128-wide MXU tile instead of a v/128 sliver (a 32x32 matmul uses 1/16th
    of the systolic array; packing 4 such graphs recovers it).  Exact: the
    off-diagonal blocks start zero and products of block-diagonal matrices
    stay block-diagonal, so each graph's closure is untouched by its
    neighbors.  The identity is added over the full packed matrix — every
    diagonal element lies inside a diagonal block.  The packed matrix is
    assembled in a VMEM scratch ref with static slice stores (Mosaic has no
    dynamic_update_slice lowering)."""
    acc_dtype = jnp.int32 if compute_dtype == jnp.int8 else jnp.float32
    gv = g * v
    row = jax.lax.broadcasted_iota(jnp.int32, (gv, gv), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (gv, gv), 1)
    eye = (row == col).astype(compute_dtype)
    # Static unroll over the packed matrices of this block: Mosaic's dot
    # lowering is 2-D, and block_b is small (VMEM-bounded), so unrolling
    # beats a loop.
    for t in range(block_b // g):
        if g == 1:
            r = jnp.maximum(adj_ref[t], eye)
        else:
            scratch_ref[...] = jnp.zeros((gv, gv), dtype=compute_dtype)
            for a in range(g):
                scratch_ref[a * v : (a + 1) * v, a * v : (a + 1) * v] = adj_ref[t * g + a]
            r = jnp.maximum(scratch_ref[...], eye)
        for _ in range(n_steps):
            p = jnp.dot(r, r, preferred_element_type=acc_dtype)
            r = (p > 0).astype(compute_dtype)
        if g == 1:
            out_ref[t] = r
        else:
            for a in range(g):
                out_ref[t * g + a] = r[a * v : (a + 1) * v, a * v : (a + 1) * v]


def pack_factor(v: int) -> int:
    """Graphs per 128-wide MXU tile (1 for V >= 128)."""
    return max(1, 128 // v)


def default_block_b(v: int, itemsize: int = 2) -> int:
    """Graphs per grid instance, sized so the live packed buffers (input
    block, packed matrix, accumulator) stay well under VMEM (~16 MB/core);
    int8 compute fits twice as many as bf16.  Always a multiple of
    pack_factor(v) so blocks split evenly into packed matrices."""
    scale = max(1, 2 // itemsize)
    if v <= 128:
        return 8 * pack_factor(v) * scale
    if v <= 256:
        return 4 * scale
    if v <= 512:
        return 2 * scale
    return 1 * scale


def _compute_dtype():
    """bf16 by default; NEMO_PALLAS_DTYPE=int8 switches the squaring chain to
    int8xint8->int32 MXU matmuls (half the VMEM, higher int throughput on
    TPUs that support it).  Both are exact for 0/1 entries."""
    import os

    name = os.environ.get("NEMO_PALLAS_DTYPE", "bfloat16")
    if name in ("int8", "i8"):
        return jnp.int8
    if name in ("bfloat16", "bf16"):
        return jnp.bfloat16
    raise ValueError(
        f"unknown NEMO_PALLAS_DTYPE {name!r} (expected bfloat16/bf16 or int8/i8)"
    )


def _edge_wave_kernel(
    state_ref, src_ref, dst_ref, mask_ref, out_ref, *, n_steps: int, block_b: int, v: int, e: int
):
    """Fused frontier waves over a [block_b] run block's edge lists: each
    step ORs into the state every node with an in-edge from the current
    state — ``state |= push(state)`` — n_steps times, entirely in VMEM.

    Mosaic has no scatter lowering, so the push is expressed one-hot-free
    as two compare-reduce passes per step: contrib[e] = any_v(state[v] &
    (src[e]==v)) gathers the edge sources, state[v] |= any_e(contrib[e] &
    (dst[e]==v)) scatters to the destinations — both are [E,V] iota
    compares + reductions, which lower.  O(E*V) per step instead of the
    dense [V,V] sweep's O(V^2): a win exactly in the sparse regime
    (E < V), and the fusion removes the per-wave HBM round-trips the XLA
    scatter path pays.  [E,V] lives in VMEM, so callers gate on e*v
    (ops/sparse_device.py:_PALLAS_WAVE_MAX_EV)."""
    col = jax.lax.broadcasted_iota(jnp.int32, (e, v), 1)
    for t in range(block_b):
        st = state_ref[t]
        oh_src = src_ref[t][:, None] == col
        oh_dst = dst_ref[t][:, None] == col
        m = mask_ref[t]
        for _ in range(n_steps):
            contrib = (oh_src & st[None, :]).any(axis=1) & m
            st = st | (oh_dst & contrib[:, None]).any(axis=0)
        out_ref[t] = st


def edge_wave_pallas(
    state: jax.Array,  # [B,V] bool
    src: jax.Array,  # [B,E] int
    dst: jax.Array,  # [B,E] int
    mask: jax.Array,  # [B,E] bool
    n_steps: int,
    block_b: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """``state |= push(state)`` fused n_steps times in VMEM (the >=0-hop
    propagation of the sparse-device frontier waves, ops/sparse_device.py).
    Monotone, so running extra steps is harmless — the fix-point loops that
    call this only need each invocation to make progress.  Bit-identical to
    the XLA scatter waves by construction (tests/test_sparse_device.py runs
    the parity in interpreter mode)."""
    b, v = state.shape
    e = src.shape[1]
    bb = min(block_b or 8, b)
    pad = (-b) % bb
    if pad:
        state = jnp.pad(state, ((0, pad), (0, 0)))
        src = jnp.pad(src, ((0, pad), (0, 0)))
        dst = jnp.pad(dst, ((0, pad), (0, 0)))
        mask = jnp.pad(mask, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(
            _edge_wave_kernel, n_steps=n_steps, block_b=bb, v=v, e=e
        ),
        out_shape=jax.ShapeDtypeStruct(state.shape, state.dtype),
        grid=(state.shape[0] // bb,),
        in_specs=[pl.BlockSpec((bb, v), lambda i: (i, 0))]
        + [pl.BlockSpec((bb, e), lambda i: (i, 0))] * 3,
        out_specs=pl.BlockSpec((bb, v), lambda i: (i, 0)),
        interpret=interpret,
    )(state, src.astype(jnp.int32), dst.astype(jnp.int32), mask)
    return out[:b]


def closure_pallas(
    adj: jax.Array,
    block_b: int | None = None,
    interpret: bool = False,
    compute_dtype=None,
    max_len: int | None = None,
) -> jax.Array:
    """Reflexive-transitive closure of [B,V,V] (or [V,V]) boolean adjacency,
    fused squaring chain in VMEM with block-diagonal MXU packing.
    Bit-identical to adjacency.closure.  max_len: static longest-path bound
    (adjacency.closure_steps)."""
    from nemo_tpu.ops.adjacency import closure_steps

    squeeze = adj.ndim == 2
    if squeeze:
        adj = adj[None]
    dt = compute_dtype or _compute_dtype()
    b, v, _ = adj.shape
    n_steps = closure_steps(v, max_len)
    g = pack_factor(v)
    bb = block_b or default_block_b(v, jnp.dtype(dt).itemsize)
    bb = max(g, (bb // g) * g)  # multiple of the pack factor
    if bb > b:
        # Shrink to the batch, keeping divisibility (padding fills the rest).
        bb = max(g, (b // g) * g if b >= g else g)
    x = adj.astype(dt)
    pad = (-b) % bb
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
    out = pl.pallas_call(
        functools.partial(
            _closure_kernel, n_steps=n_steps, block_b=bb, v=v, g=g, compute_dtype=dt
        ),
        out_shape=jax.ShapeDtypeStruct(x.shape, dt),
        grid=(x.shape[0] // bb,),
        in_specs=[pl.BlockSpec((bb, v, v), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((bb, v, v), lambda i: (i, 0, 0)),
        # The packed-assembly scratch exists only when packing happens
        # (g>1): at V>=128 it would idle 2-8 MB of the VMEM budget the
        # large-V blocks need.
        scratch_shapes=[pltpu.VMEM((g * v, g * v), dt)] if g > 1 else [],
        interpret=interpret,
    )(x)
    res = out[:b] > 0
    return res[0] if squeeze else res
