"""Shared dense-adjacency primitives for the batched graph kernels.

TPU-first design: per-run provenance graphs are small (tens to a few hundred
nodes), so reachability is cheapest as *batched dense boolean matmuls on the
MXU* — frontier steps are [B,V]x[B,V,V] einsums and transitive closure is
log2(V) squarings of [B,V,V] bf16 matrices — rather than as the pointer-chasing
BFS a CPU graph store performs (the Cypher `-[*0..]->` matches of
preprocessing.go:18, prototype.go:12, differential-provenance.go:26).  The
run axis B is the data-parallel axis sharded across the TPU mesh.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def build_adjacency(
    edge_src: jax.Array, edge_dst: jax.Array, edge_mask: jax.Array, v: int
) -> jax.Array:
    """Edge lists [B,E] -> dense boolean adjacency [B,V,V]."""
    b = edge_src.shape[0]
    adj = jnp.zeros((b, v, v), dtype=bool)
    b_idx = jnp.arange(b)[:, None]
    return adj.at[b_idx, edge_src, edge_dst].max(edge_mask)


def bool_matmul(x: jax.Array, y: jax.Array) -> jax.Array:
    """Boolean matrix product on the MXU: bf16 multiply, f32 accumulate,
    threshold.  Exact because entries are 0/1 and accumulation is f32.

    bf16 is kept on the CPU fallback too (r5, measured): isolated 8-hop
    chains run 3x faster in f32 on XLA:CPU (bf16 matmul is emulated), but
    the production fused step shows NO e2e difference (sweep 2.34 s bf16
    vs 2.54 s f32 at the 1x stress shape) — its CPU wall lives in the
    scatter/one-hot passes, not the hop einsums, so a platform-split
    dtype would churn every compiled signature for nothing."""
    prod = jnp.einsum(
        "...ik,...kj->...ij",
        x.astype(jnp.bfloat16),
        y.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    return prod > 0.5


def step_forward(frontier: jax.Array, adj: jax.Array) -> jax.Array:
    """One BFS hop: nodes with an in-edge from the frontier.  [B,V]x[B,V,V]."""
    prod = jnp.einsum(
        "...v,...vw->...w",
        frontier.astype(jnp.bfloat16),
        adj.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    return prod > 0.5


def step_backward(frontier: jax.Array, adj: jax.Array) -> jax.Array:
    """One reverse hop: nodes with an out-edge into the frontier."""
    prod = jnp.einsum(
        "...w,...vw->...v",
        frontier.astype(jnp.bfloat16),
        adj.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    return prod > 0.5


def resolve_closure_impl(impl: str | None = None) -> str:
    """Resolve a closure implementation request to a concrete one:
    None/"auto" -> NEMO_CLOSURE_IMPL env, defaulting to xla.  The single
    resolution point for closure(), the fused analysis step's pre-jit
    resolution, and the benchmark.

    auto picks xla because it is the MEASURED winner (VERDICT r3 weak #1):
    v5e sweep, B=1700, 32 chains per dispatch (xla/pallas time ratio —
    >1 means pallas faster), after giving the pallas kernel block-diagonal
    MXU packing (ops/pallas_kernels.py):

        V=32  full 0.95x  d16 1.00x
        V=64  full 0.88x  d16 1.00x
        V=128 full 0.74x  d16 0.94x
        V=256 full 0.88x  d16 0.88x

    The closure at production shapes is dispatch/bandwidth-trivial
    (~0.5 GFLOP, ~40 MB for a [1700,32,32] chain), so the fused-chain
    kernel's saved HBM round-trips never amortize its weaker pipelining;
    XLA's batched matmul wins or ties at every shape.  The pallas kernel
    stays available via NEMO_CLOSURE_IMPL=pallas (and is the only fused
    option under memory pressure studies); the depth-bounded step count
    (closure_steps) benefits both equally.

    FINAL STATUS (r5, accepting VERDICT r4 weak #7 as-is): the kernel has
    no production shape where it wins, and this is a PROPERTY OF THE
    WORKLOAD, not an unfinished search — every closure this framework
    computes is small-V/batched (dense buckets cap at NEMO_GIANT_V;
    beyond that the giant path is closure-free by design, and the r5
    crossover routes CPU fallbacks to the sparse host analysis, which
    shrinks pallas's domain further).  A workload where a fused Mosaic
    closure could win — single graphs at V in the thousands with dense
    connectivity — is one the domain never produces (provenance graphs
    that big are deep @next chains, which contract).  The kernel is kept
    as a measured reference implementation and memory-pressure option,
    exercised by tests/test_pallas.py in interpreter mode."""
    impl = impl or os.environ.get("NEMO_CLOSURE_IMPL", "auto")
    if impl == "auto":
        impl = "xla"
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown closure impl {impl!r} (expected auto, xla, or pallas)")
    return impl


def closure_steps(v: int, max_len: int | None = None) -> int:
    """Squaring count for an exact >=0-hop closure: (A|I)^(2^k) covers every
    path of length <= 2^k, so k = ceil(log2(bound)) suffices when `bound`
    >= the longest path (in edges).  max_len supplies a tight bound (e.g.
    the corpus max_depth for DIRECTED closures — DAG paths never exceed the
    longest path; undirected component closures must NOT pass one, their
    diameter is not bounded by directed depth); default v-1."""
    bound = min(v - 1, max_len) if max_len else v - 1
    return max(1, (max(1, bound) - 1).bit_length())


def closure(adj: jax.Array, impl: str | None = None, max_len: int | None = None) -> jax.Array:
    """Reflexive-transitive closure (>=0 hops) by squaring.

    impl: "xla" (einsum chain, one HBM round-trip per squaring; GSPMD can
    partition it, so it is the only legal choice under a sharded jit),
    "pallas" (fused VMEM-resident chain, ops/pallas_kernels.py; interpreter
    mode off-TPU), or "auto"/None (NEMO_CLOSURE_IMPL env, defaulting to
    xla — the measured winner, see resolve_closure_impl).  max_len: static
    longest-path bound in edges (closure_steps) — cuts the squaring count
    several-fold when the corpus depth is far below V."""
    impl = resolve_closure_impl(impl)
    if impl == "pallas":
        from nemo_tpu.ops.pallas_kernels import closure_pallas

        return closure_pallas(adj, interpret=jax.default_backend() != "tpu", max_len=max_len)
    v = adj.shape[-1]
    eye = jnp.eye(v, dtype=bool)
    r = adj | eye
    for _ in range(closure_steps(v, max_len)):
        r = bool_matmul(r, r)
    return r


def reach_ge1(adj: jax.Array, clo: jax.Array) -> jax.Array:
    """>=1-hop reachability from the >=0-hop closure: adj @ closure."""
    return bool_matmul(adj, clo)


def in_degree_any(adj: jax.Array) -> jax.Array:
    """[B,V] bool: node has any incoming edge."""
    return adj.any(axis=-2)


def out_degree_any(adj: jax.Array) -> jax.Array:
    """[B,V] bool: node has any outgoing edge."""
    return adj.any(axis=-1)


def table_bitset(mask: jax.Array, table_id: jax.Array, num_tables: int) -> jax.Array:
    """[B,V] node mask -> [B,T] per-table any-bitset (table_id -1 = padding)."""
    tid = jnp.clip(table_id, 0, num_tables - 1)
    one_hot = jax.nn.one_hot(tid, num_tables, dtype=bool) & (table_id >= 0)[..., None]
    return jnp.any(one_hot & mask[..., None], axis=-2)


def table_min(
    values: jax.Array, mask: jax.Array, table_id: jax.Array, num_tables: int, fill: int
) -> jax.Array:
    """[B,V] int values -> [B,T] per-table min over masked nodes (else fill)."""
    tid = jnp.clip(table_id, 0, num_tables - 1)
    one_hot = jax.nn.one_hot(tid, num_tables, dtype=bool) & (table_id >= 0)[..., None]
    sel = one_hot & mask[..., None]
    vals = jnp.where(sel, values[..., None], fill)
    return jnp.min(vals, axis=-2)
