"""Graph simplification kernels: clean-copy restriction and @next chain
contraction.

Array form of the reference's SimplifyProv pass
(graphing/preprocessing.go:351-387; semantics per backend/base.py):

  * clean_masks: keep all goals; keep rules with both an incoming and an
    outgoing goal edge; keep edge g->r iff r has an out-goal, r->g iff r has
    an in-goal (the Goal-[*0..]->Goal path restriction of
    preprocessing.go:17-27, expressed as degree masks on the bipartite graph).

  * collapse_chains: contract each connected component (with >=2 next rules)
    of the {type==next rules + goals strictly between next rules} subgraph
    into a single collapsed rule occupying the slot of the component's
    minimum-index head rule; external goal predecessors of head rules and
    goal successors of tail rules rewire to it; everything else in the
    component dies (preprocessing.go:66-348).  Component labeling runs as a
    transitive closure on the MXU; edge rewiring is two boolean matmuls that
    move columns/rows onto the representative slot.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nemo_tpu.graphs.packed import TYPE_COLLAPSED, TYPE_NEXT

from .adjacency import bool_matmul, closure, step_backward, step_forward


def clean_masks(
    adj: jax.Array, is_goal: jax.Array, node_mask: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Returns (adj_clean [B,V,V], alive [B,V])."""
    goal = is_goal & node_mask
    has_in_goal = step_forward(goal, adj)  # rule has an incoming goal edge
    has_out_goal = step_backward(goal, adj)  # rule has an outgoing goal edge
    is_rule = ~is_goal & node_mask
    alive = goal | (is_rule & has_in_goal & has_out_goal)
    # Edge u->v: from a goal, keep iff rule v has an out-goal; from a rule u,
    # keep iff u has an in-goal.
    keep = jnp.where(goal[..., None], has_out_goal[..., None, :], has_in_goal[..., None])
    adj_clean = adj & keep & alive[..., None] & alive[..., None, :]
    return adj_clean, alive


def _labels_closure(und, member, v, idx, closure_impl):
    """Component labels = min member index reachable in the undirected
    member subgraph (closure on the MXU; log2(V) squarings, O(V^3 log V))."""
    comp_reach = closure(und, impl=closure_impl)  # includes identity
    return jnp.min(
        jnp.where(comp_reach & member[..., None], idx[None, :, None], v), axis=-2
    )  # [B,V]; == v for non-members


def _labels_prop(und, member, v, idx, iters):
    """Min-label propagation, O(iters * V^2).  Exact when iters >= the
    undirected diameter of the widest member component."""
    lab0 = jnp.where(member, idx, v)

    def prop(_, lb):
        neigh = jnp.min(jnp.where(und, lb[..., None, :], v), axis=-1)
        return jnp.minimum(lb, neigh)

    lab = jax.lax.fori_loop(0, iters, prop, lab0)
    return jnp.where(member, lab, v)


def chains_linear_host(is_goal, node_mask, type_id, edge_src, edge_dst, edge_mask) -> bool:
    """Host-side (numpy) batched mirror of giant_plan's linearity check over
    [B,V]/[B,E] packed batch arrays: True iff EVERY run's @next chain-member
    subgraph (after the clean_masks restriction) has member in/out degree
    <= 1 — the precondition for the O(V log V) pointer-doubling labels in
    collapse_chains(comp_doubling=True).

    Conservative by construction: duplicate edge-list entries inflate the
    host degree counts (the device adjacency dedups them), so a duplicated
    chain edge can only flip the answer to False — costing the closure
    fallback, never correctness.  All scatters are flat bincounts (the
    ufunc.at equivalents are orders of magnitude slower at stress scale)."""
    import numpy as np

    is_goal = np.asarray(is_goal)
    node_mask = np.asarray(node_mask)
    type_id = np.asarray(type_id)
    src = np.asarray(edge_src).astype(np.int64)
    dst = np.asarray(edge_dst).astype(np.int64)
    em = np.asarray(edge_mask).astype(bool)
    b, v = is_goal.shape
    rows = np.broadcast_to(np.arange(b)[:, None], src.shape)
    flat_src = (rows * v + src).ravel()
    flat_dst = (rows * v + dst).ravel()

    def scatter_any(flat_idx, vals) -> "np.ndarray":
        return (
            np.bincount(flat_idx[vals.ravel()], minlength=b * v).reshape(b, v) > 0
        )

    goal = is_goal & node_mask
    src_goal = np.take(goal.ravel(), flat_src).reshape(src.shape) & em
    dst_goal = np.take(goal.ravel(), flat_dst).reshape(src.shape) & em
    has_in_goal = scatter_any(flat_dst, src_goal)
    has_out_goal = scatter_any(flat_src, dst_goal)
    rule_alive = ~is_goal & node_mask & has_in_goal & has_out_goal
    alive = goal | rule_alive
    # clean_masks edge keep: from a goal iff the rule dst has an out-goal;
    # from a rule iff it has an in-goal; endpoints alive.
    keep = (
        em
        & np.where(
            np.take(goal.ravel(), flat_src).reshape(src.shape),
            np.take(has_out_goal.ravel(), flat_dst).reshape(src.shape),
            np.take(has_in_goal.ravel(), flat_src).reshape(src.shape),
        )
        & np.take(alive.ravel(), flat_src).reshape(src.shape)
        & np.take(alive.ravel(), flat_dst).reshape(src.shape)
    )
    next_rule = ~is_goal & alive & (type_id == TYPE_NEXT)
    in_from_next = scatter_any(flat_dst, np.take(next_rule.ravel(), flat_src).reshape(src.shape) & keep)
    out_to_next = scatter_any(flat_src, np.take(next_rule.ravel(), flat_dst).reshape(src.shape) & keep)
    member = next_rule | (goal & alive & in_from_next & out_to_next)
    member_edge = (
        keep
        & np.take(member.ravel(), flat_src).reshape(src.shape)
        & np.take(member.ravel(), flat_dst).reshape(src.shape)
    )
    succ = np.bincount(flat_src[member_edge.ravel()], minlength=b * v).reshape(b, v)
    pred = np.bincount(flat_dst[member_edge.ravel()], minlength=b * v).reshape(b, v)
    return bool(((succ <= 1) | ~member).all() and ((pred <= 1) | ~member).all())


def pair_chains_linear(pre, post) -> bool:
    """chains_linear_host over a (pre, post) batch pair — the reduction the
    object-ingest dispatch sites use.  The packed-first path reads per-run
    flags computed at parse time by the C++ mirror of the same criterion
    (native/nemo_native.cpp:graph_chain_linear); the two implementations
    are pinned together by the per-run parity tests in
    tests/test_fast_ingest.py (case-study + zigzag corpora), which is the
    contract keeping the measured and deployed flags from diverging."""
    return all(
        chains_linear_host(
            b.is_goal, b.node_mask, b.type_id, b.edge_src, b.edge_dst, b.edge_mask
        )
        for b in (pre, post)
    )


def _labels_doubling(a, member, v, idx):
    """Pointer-doubling along the DIRECTED member successor, O(V log V)
    after one O(V^2) argmax: every member's pointer converges to its chain
    tail in log2(V) jumps, and the tail index is the component label.
    Exact ONLY for linear chains (each member has <= 1 member successor) —
    the shape @next persistence rules generate (`t(C+1)@next :- t(C)`,
    SURVEY.md §5); the giant-graph dispatcher verifies linearity host-side
    before choosing this method (parallel/giant.py)."""
    succ_mask = a & member[..., None] & member[..., None, :]
    has_succ = succ_mask.any(axis=-1)
    p = jnp.where(has_succ, jnp.argmax(succ_mask, axis=-1), idx)  # [B,V]

    def jump(_, p):
        return jnp.take_along_axis(p, p, axis=-1)

    n_iters = max(1, (v - 1).bit_length())
    p = jax.lax.fori_loop(0, n_iters, jump, p)
    return jnp.where(member, p, v)


def collapse_chains(
    adj: jax.Array,  # [B,V,V] clean adjacency
    is_goal: jax.Array,  # [B,V]
    type_id: jax.Array,  # [B,V]
    alive: jax.Array,  # [B,V]
    closure_impl: str = "auto",
    comp_iters: int | None = None,
    comp_doubling: bool = False,
    rewire: str = "matmul",
    comp_labels: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (adj_new, alive_new, type_new).

    Component labeling (any consistent member-index-valued label works):
      default            all-pairs closure on the MXU — exact for ANY
                         member structure; right for the small-V batched
                         buckets;
      comp_labels=<arr>  precomputed [B,V] labels (host union-find — the
                         giant path's exact labels for arbitrary member
                         structures; no bounded device iteration is sound
                         there, see parallel/giant.py:giant_plan);
      comp_iters=<int>   bounded min-label propagation, O(iters * V^2) —
                         exact ONLY when iters >= the widest member
                         component's undirected diameter, which the caller
                         must guarantee;
      comp_doubling      pointer doubling, O(V log V) — linear chains only
                         (caller-verified, ops/simplify.py:
                         chains_linear_host / parallel/giant.py:giant_plan).

    rewire: "matmul" moves pred/succ edges onto representatives with two
    boolean matmuls (MXU, O(V^3) — fine batched at small V); "scatter"
    uses column/row scatters instead (O(V^2) — the giant path)."""
    v = adj.shape[-1]
    idx = jnp.arange(v)

    a = adj & alive[..., None] & alive[..., None, :]
    next_rule = ~is_goal & alive & (type_id == TYPE_NEXT)
    in_from_next = step_forward(next_rule, a)
    out_to_next = step_backward(next_rule, a)
    chain_goal = is_goal & alive & in_from_next & out_to_next
    member = next_rule | chain_goal

    if comp_labels is not None:
        lab = jnp.where(member, comp_labels, v)
    elif comp_doubling:
        lab = _labels_doubling(a, member, v, idx)
    else:
        und = (a | jnp.swapaxes(a, -1, -2)) & member[..., None] & member[..., None, :]
        if comp_iters is None:
            lab = _labels_closure(und, member, v, idx, closure_impl)
        else:
            lab = _labels_prop(und, member, v, idx, comp_iters)
    lab_c = jnp.clip(lab, 0, v - 1)

    in_from_member = step_forward(member, a)
    out_to_member = step_backward(member, a)
    head = next_rule & ~in_from_member
    tail = next_rule & ~out_to_member

    one_hot_lab = (lab[..., None] == idx) & member[..., None]  # [B,V,C]
    rep_per_comp = jnp.min(
        jnp.where(one_hot_lab & head[..., None], idx[:, None], v), axis=-2
    )  # [B,C] min head index, v if no head
    n_rules_per_comp = jnp.sum(one_hot_lab & next_rule[..., None], axis=-2)
    collapsible_comp = (n_rules_per_comp >= 2) & (rep_per_comp < v)

    node_collapsible = member & jnp.take_along_axis(collapsible_comp, lab_c, axis=-1)
    rep_of_node = jnp.where(
        node_collapsible, jnp.take_along_axis(rep_per_comp, lab_c, axis=-1), idx
    )
    is_rep = node_collapsible & (idx == rep_of_node)
    dies = node_collapsible & ~is_rep

    # Edge moves onto the representative slot: external-goal predecessors of
    # heads rewire to the rep's column, goal successors of tails to its row.
    ext_goal = is_goal & alive & ~member
    if rewire == "matmul":
        head_map = (rep_of_node[..., None] == idx) & head[..., None] & node_collapsible[..., None]
        tail_map = (rep_of_node[..., None] == idx) & tail[..., None] & node_collapsible[..., None]
        pred_edges = bool_matmul(a & ext_goal[..., None], head_map)  # goal -> rep
        succ_edges = bool_matmul(
            jnp.swapaxes(tail_map, -1, -2), a & ext_goal[..., None, :]
        )  # rep -> goal
    elif rewire == "scatter":
        pred_src = a & ext_goal[..., None] & (head & node_collapsible)[..., None, :]
        succ_src = a & (tail & node_collapsible)[..., None] & ext_goal[..., None, :]
        zeros = jnp.zeros_like(a)

        def move_cols(m, rep):
            return jnp.zeros(m.shape, dtype=bool).at[:, rep].max(m)

        def move_rows(m, rep):
            return jnp.zeros(m.shape, dtype=bool).at[rep, :].max(m)

        pred_edges = zeros | jax.vmap(move_cols)(pred_src, rep_of_node)
        succ_edges = zeros | jax.vmap(move_rows)(succ_src, rep_of_node)
    else:
        raise ValueError(f"unknown rewire {rewire!r} (expected matmul or scatter)")

    kill = node_collapsible
    adj_new = (a & ~kill[..., None] & ~kill[..., None, :]) | pred_edges | succ_edges
    alive_new = alive & ~dies
    type_new = jnp.where(is_rep, TYPE_COLLAPSED, type_id)
    return adj_new, alive_new, type_new
