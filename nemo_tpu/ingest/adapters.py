"""Fault-injector ingest adapters — the injector-agnostic seam (ISSUE 15).

The reference binary hard-wires one ``FaultInjector`` implementation
(faultinjectors/molly.go); everything downstream of ``main.go:106`` only
touches the interface surface (runs, iteration lists, failure spec,
messages of failed runs).  This module reproduces that seam for the
rebuild: a :class:`FaultInjector` adapter enumerates a sweep directory's
runs and loads them into the SAME :class:`~nemo_tpu.ingest.molly.MollyOutput`
product every downstream layer consumes — corpus store populate, delta
analysis, result cache, streaming, synthesis, serving, fleet — so a new
injector front end is ingest-only work, with no adapter-specific branches
below this seam.

Two implementations ship:

  * :class:`MollyInjector` — the existing Molly loader
    (ingest/molly.py:load_molly_output), now the seam's first
    implementation.  ``native_capable``: the C++ packed-first ETL applies.
  * :class:`TraceJsonInjector` — a generic trace-JSON / Jepsen-style-history
    front end: ONE ``trace.json`` document per sweep instead of Molly's
    per-run file fan-out, with message histories and neutral provenance
    graphs (schema below).  Proves the seam: a non-Molly corpus flows
    end-to-end (store, analysis, report, sidecar AnalyzeDir) unchanged.

Selection: ``NEMO_INJECTOR`` / CLI ``--injector`` names an adapter
(``molly``, ``trace-json``) or ``auto`` (default) — auto sniffs the
directory layout (``runs.json`` -> molly, ``trace.json`` -> trace-json).

The trace-JSON schema (``<dir>/trace.json``)::

    {
      "format": "nemo-trace-v1",
      "name": "optional sweep name",
      "spec": {"eot": 6, "eff": 4, "max_crashes": 1, "nodes": ["C","a","b"]},
      "runs": [
        {
          "id": 0,
          "outcome": "ok" | "violation",      # or an explicit "status"
          "faults": {"omissions": [{"from":"a","to":"b","at":3}],
                      "crashes":   [{"node":"a","at":3}]},
          "history": [                         # Jepsen-style op log; only
            {"op": "send", "table": "request", # send ops carry messages
             "from": "C", "to": "a", "at": 1, "delivered_at": 2}, ...],
          "holds": {"pre": [4,5,6], "post": [5,6]},  # invariant timesteps
          "tables": {...},                     # optional raw model tables
                                               # (verbatim; wins over holds)
          "provenance": {
            "pre":  {"nodes": [{"id":"n0","kind":"fact","table":"pre",
                                "label":"pre(foo)","time":6},
                               {"id":"n1","kind":"rule","table":"acked",
                                "rule_type":"async", ...}, ...],
                     "deps": [["n0","n1"], ...]},
            "post": {...}
          }
        }, ...
      ]
    }

Conversion rules: ``outcome: "ok"`` maps to the exact status ``"success"``
(molly.go:52-57's partition rule); ``holds`` timestep lists become
single-column model rows whose LAST column is the timestep (the holds-map
keying contract, molly.go:38-48); provenance node ids are namespaced
``run_<id>_{pre,post}_<origID>`` exactly like Molly's (molly.go:92-107).
Trace sweeps carry no spacetime DOT files — the hazard figures render the
:meth:`~nemo_tpu.ingest.molly.MollyOutput.spacetime_dot_text` fallback,
synthesized deterministically from each run's message history and failure
spec.
"""

from __future__ import annotations

import json
import os

from nemo_tpu import obs
from nemo_tpu.obs import log as _obs_log

from .datatypes import (
    CrashFailure,
    Edge,
    FailureSpec,
    Goal,
    Message,
    MessageLoss,
    Model,
    ProvData,
    Rule,
    RunData,
)
from .molly import (
    MollyOutput,
    _namespace_prov,
    attach_run_metadata,
    load_molly_output,
    quarantine_record,
)

_log = _obs_log.get_logger("nemo.ingest")

TRACE_FILE = "trace.json"
TRACE_FORMAT = "nemo-trace-v1"


class FaultInjector:
    """One fault-injector front end: how a sweep directory's runs are
    enumerated and parsed into a :class:`MollyOutput`.

    Subclasses define the class attributes and :meth:`load`; the classmethod
    surface (:meth:`sniff`, :meth:`count_runs`, :meth:`poll_token`,
    :meth:`materialize_prefix`) is what layout-aware tooling ABOVE the seam
    — the live watcher's change detection and the replay driver — consults,
    so those stay injector-agnostic too."""

    #: Registry name (the ``--injector`` / ``NEMO_INJECTOR`` vocabulary).
    name: str = ""
    #: The file whose presence identifies the layout and whose stat cheaply
    #: signals growth (the watcher's poll reads it, molly: runs.json).
    index_file: str = ""
    #: Whether the C++ packed-first ETL (ingest/native.py) can parse this
    #: layout directly.  False routes the packed path through :meth:`load`
    #: plus the store populate — the lib-less-host precedent.
    native_capable: bool = False

    @classmethod
    def sniff(cls, corpus_dir: str) -> bool:
        return os.path.isfile(os.path.join(corpus_dir, cls.index_file))

    def load(self, corpus_dir: str, quarantine: bool | None = None) -> MollyOutput:
        raise NotImplementedError

    def pack_steps(self, corpus_dir: str):
        """Packed-array ingest through the seam: the (pre BatchArrays,
        post BatchArrays, static kwargs) triple every analysis dispatch —
        local or remote — consumes.  Default route: adapter load then the
        pure-Python pack (the lib-less-host path, any layout);
        :class:`MollyInjector` overrides with the packed-first host ETL.
        The client chunked-upload paths (service/client.py:analyze_dir,
        analyze_dir_pipelined) call THIS instead of a Molly-only packer,
        so a non-Molly corpus streams to the sidecar unchanged."""
        from nemo_tpu.models.pipeline_model import pack_molly_for_step

        return pack_molly_for_step(self.load(corpus_dir))

    @classmethod
    def count_runs(cls, corpus_dir: str) -> int:
        """Cheap run count (index parse, no provenance) — watcher bookkeeping."""
        raise NotImplementedError

    @classmethod
    def poll_token(cls, corpus_dir: str) -> tuple:
        """Cheap change signature for the watcher's debounced poll: the dir
        mtime plus the index file's (size, mtime).  Two equal tokens mean
        "no new runs appeared and the index is settled"; any append — Molly
        rewriting runs.json, a trace producer re-flushing trace.json —
        moves it.  Never parses anything."""
        try:
            dir_m = os.stat(corpus_dir).st_mtime_ns
        except OSError:
            dir_m = -1
        try:
            st = os.stat(os.path.join(corpus_dir, cls.index_file))
            idx = (st.st_size, st.st_mtime_ns)
        except OSError:
            idx = (-1, -1)
        return (dir_m, *idx)

    @classmethod
    def materialize_prefix(cls, src_dir: str, dst_dir: str, n_runs: int) -> None:
        """Materialize the first ``n_runs`` runs of a finished sweep at
        ``src_dir`` into ``dst_dir``, monotonically (existing run content
        untouched) — the replay driver's per-generation step."""
        raise NotImplementedError

    @classmethod
    def index_runs(cls, corpus_dir: str):
        """Per-entry access to the raw index document, for layouts whose
        WHOLE sweep lives inside the index file (trace.json): returns
        ``(n_entries, parse, head)`` where ``parse(pos) -> RunData`` (may
        raise on a malformed entry) and ``head(pos) -> (iteration,
        success)`` reads just the baked-in identity pair.  The corpus
        store's index-delta append path (store/__init__.py) consumes this
        to confirm the stored entries unchanged and pack ONLY the appended
        tail — the watch loop's O(new runs) growth story for non-Molly
        injectors.  None (the default) means the layout has no
        single-document growth story; Molly's per-run files ride the
        dedicated runs.json append path instead."""
        return None


class MollyInjector(FaultInjector):
    """The Molly front end — the seam's first implementation, delegating to
    the reference-parity loader (ingest/molly.py:load_molly_output, whose
    invariants that module documents)."""

    name = "molly"
    index_file = "runs.json"
    native_capable = True

    def load(self, corpus_dir: str, quarantine: bool | None = None) -> MollyOutput:
        return load_molly_output(corpus_dir, quarantine=quarantine)

    def pack_steps(self, corpus_dir: str):
        # Packed-first: the C++ engine or a warm corpus-store mmap when
        # either can serve, the pure-Python pack otherwise — native.py
        # owns that fallback ladder.
        from nemo_tpu.ingest.native import pack_molly_dir

        return pack_molly_dir(corpus_dir)

    @classmethod
    def count_runs(cls, corpus_dir: str) -> int:
        with open(os.path.join(corpus_dir, "runs.json"), encoding="utf-8") as fh:
            return len(json.load(fh))

    @classmethod
    def materialize_prefix(cls, src_dir: str, dst_dir: str, n_runs: int) -> None:
        from nemo_tpu.models.synth import grow_corpus_dir

        grow_corpus_dir(src_dir, dst_dir, n_runs)


def _trace_prov(graph: dict) -> ProvData:
    """Neutral ``{"nodes": [...], "deps": [...]}`` graph -> ProvData.  Node
    ids stay the producer's (namespacing happens afterwards, shared with
    the Molly path); a dep naming an unknown node id is a schema violation
    (quarantined per run by the caller)."""
    nodes = graph.get("nodes") or []
    deps = graph.get("deps") or []
    prov = ProvData()
    known: set[str] = set()
    for n in nodes:
        nid = str(n["id"])
        known.add(nid)
        kind = n.get("kind", "fact")
        if kind == "rule":
            prov.rules.append(
                Rule(
                    id=nid,
                    label=n.get("label", n.get("table", "")),
                    table=n.get("table", ""),
                    type=n.get("rule_type", ""),
                )
            )
        elif kind == "fact":
            prov.goals.append(
                Goal(
                    id=nid,
                    label=n.get("label", ""),
                    table=n.get("table", ""),
                    time=str(n.get("time", "")),
                    sender=n.get("sender", ""),
                    receiver=n.get("receiver", ""),
                )
            )
        else:
            raise ValueError(f"trace node {nid!r} has unknown kind {kind!r}")
    for dep in deps:
        src, dst = str(dep[0]), str(dep[1])
        if src not in known or dst not in known:
            raise ValueError(f"trace dep {dep!r} names an undeclared node")
        prov.edges.append(Edge(src=src, dst=dst))
    return prov


def _holds_rows(holds) -> list[list[str]]:
    """Trace ``holds`` entry -> model-table rows.  Timestep ints become
    single-column rows; list entries pass through verbatim (full-fidelity
    producers).  Either way the LAST column is the timestep string the
    holds-map keying reads (molly.go:38-48)."""
    rows = []
    for h in holds or []:
        rows.append([str(c) for c in h] if isinstance(h, (list, tuple)) else [str(h)])
    return rows


def _trace_run(spec: dict, raw: dict) -> RunData:
    """One trace run entry -> RunData (provenance attached, un-namespaced)."""
    iteration = int(raw["id"])
    status = raw.get("status")
    if status is None:
        status = "success" if raw.get("outcome", "ok") == "ok" else "fail"
    faults = raw.get("faults") or {}
    fs = FailureSpec(
        eot=int(spec.get("eot", 0)),
        eff=int(spec.get("eff", 0)),
        max_crashes=int(spec.get("max_crashes", 0)),
        nodes=list(spec["nodes"]) if spec.get("nodes") is not None else None,
        crashes=[
            CrashFailure(node=c["node"], time=int(c["at"]))
            for c in faults.get("crashes") or []
        ],
        omissions=[
            MessageLoss(src=o["from"], dst=o["to"], time=int(o["at"]))
            for o in faults.get("omissions") or []
        ],
    )
    if raw.get("tables") is not None:
        tables = {k: [list(r) for r in v] for k, v in raw["tables"].items()}
    else:
        holds = raw.get("holds") or {}
        tables = {
            "pre": _holds_rows(holds.get("pre")),
            "post": _holds_rows(holds.get("post")),
        }
    messages = [
        Message(
            content=ev.get("table", ""),
            send_node=ev.get("from", ""),
            recv_node=ev.get("to", ""),
            send_time=int(ev.get("at", 0)),
            recv_time=int(ev.get("delivered_at", 0)),
        )
        for ev in raw.get("history") or []
        if ev.get("op") == "send"
    ]
    run = RunData(
        iteration=iteration,
        status=status,
        failure_spec=fs,
        model=Model(tables=tables),
        messages=messages,
    )
    prov = raw.get("provenance") or {}
    for cond, attr in (("pre", "pre_prov"), ("post", "post_prov")):
        p = _trace_prov(prov.get(cond) or {})
        _namespace_prov(p, iteration, cond)
        setattr(run, attr, p)
    return run


class TraceJsonInjector(FaultInjector):
    """Generic trace-JSON / Jepsen-style-history front end (schema in the
    module docstring): one ``trace.json`` document carries the whole sweep.
    Per-run conversion failures quarantine exactly like the Molly loader's
    per-run parse failures; the document itself failing to parse raises
    (no per-run boundary to isolate, the runs.json precedent)."""

    name = "trace-json"
    index_file = TRACE_FILE

    def load(self, corpus_dir: str, quarantine: bool | None = None) -> MollyOutput:
        from nemo_tpu.utils.env import quarantine_enabled

        if quarantine is None:
            quarantine = quarantine_enabled()
        doc = _read_trace(corpus_dir)
        out = MollyOutput(
            run_name=os.path.basename(os.path.normpath(corpus_dir)),
            output_dir=corpus_dir,
            # The trace layout ships no spacetime DOT files: hazard
            # diagrams synthesize from each run's message history.
            ships_spacetime_dots=False,
        )
        spec = doc.get("spec") or {}
        for i, raw in enumerate(doc.get("runs") or []):
            try:
                run = _trace_run(spec, raw)
            except Exception as ex:
                if not quarantine:
                    raise
                rid = raw.get("id") if isinstance(raw, dict) else None
                rec = quarantine_record(
                    i, rid if isinstance(rid, int) else None, TRACE_FILE, ex
                )
                out.quarantined.append(rec)
                obs.metrics.inc("ingest.quarantined")
                _log.warning(
                    "ingest.quarantined",
                    corpus=corpus_dir,
                    position=rec["position"],
                    file=rec["file"],
                    error=rec["error"],
                )
                continue
            out.runs.append(run)
            attach_run_metadata(out, run)
        if not out.runs:
            raise RuntimeError(
                f"trace corpus {corpus_dir} has no loadable runs"
                + (
                    f" ({len(out.quarantined)} quarantined; first: "
                    f"{out.quarantined[0]['error']})"
                    if out.quarantined
                    else ""
                )
            )
        return out

    @classmethod
    def count_runs(cls, corpus_dir: str) -> int:
        return len(_read_trace(corpus_dir).get("runs") or [])

    @classmethod
    def materialize_prefix(cls, src_dir: str, dst_dir: str, n_runs: int) -> None:
        doc = _read_trace(src_dir)
        doc["runs"] = (doc.get("runs") or [])[:n_runs]
        os.makedirs(dst_dir, exist_ok=True)
        with open(os.path.join(dst_dir, TRACE_FILE), "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)

    @classmethod
    def index_runs(cls, corpus_dir: str):
        doc = _read_trace(corpus_dir)
        spec = doc.get("spec") or {}
        raws = doc.get("runs") or []

        def parse(pos: int) -> RunData:
            return _trace_run(spec, raws[pos])

        def head(pos: int) -> tuple[int, bool]:
            raw = raws[pos]
            status = raw.get("status")
            if status is None:
                status = "success" if raw.get("outcome", "ok") == "ok" else "fail"
            return int(raw["id"]), status == "success"

        return len(raws), parse, head


def _read_trace(corpus_dir: str) -> dict:
    with open(os.path.join(corpus_dir, TRACE_FILE), encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise ValueError(f"{TRACE_FILE} must be a JSON object")
    return doc


#: Sniff order matters only for pathological dirs carrying BOTH index
#: files; molly wins there (the richer layout).
INJECTORS: dict[str, type[FaultInjector]] = {
    MollyInjector.name: MollyInjector,
    TraceJsonInjector.name: TraceJsonInjector,
}


def injector_arg(arg: str | None = None) -> str:
    """Resolve the configured injector name: explicit ``arg`` (CLI) wins,
    then ``NEMO_INJECTOR``, default ``auto``.  Loud on junk — an unknown
    injector name silently degrading to auto-sniff would mask typos."""
    val = (arg or os.environ.get("NEMO_INJECTOR") or "auto").strip().lower()
    if val not in ("auto", *INJECTORS):
        raise ValueError(
            f"unknown injector {val!r} (expected auto, "
            + ", ".join(INJECTORS)
            + ")"
        )
    return val


def resolve_injector(corpus_dir: str, arg: str | None = None) -> FaultInjector:
    """The ingest seam's dispatch: an adapter instance for ``corpus_dir``.
    ``auto`` sniffs the layout; an explicit name is trusted (its load will
    fail loudly on a wrong layout).  Counted per resolution so the
    telemetry shows which front ends fed the system."""
    name = injector_arg(arg)
    if name == "auto":
        for cand in INJECTORS.values():
            if cand.sniff(corpus_dir):
                name = cand.name
                break
        else:
            raise ValueError(
                f"cannot sniff a fault-injector layout in {corpus_dir}: "
                f"expected one of "
                + ", ".join(
                    f"{c.index_file} ({c.name})" for c in INJECTORS.values()
                )
                + "; pin one with --injector / NEMO_INJECTOR"
            )
    obs.metrics.inc(f"ingest.injector.{name}")
    return INJECTORS[name]()


def load_output(corpus_dir: str, arg: str | None = None) -> MollyOutput:
    """Object-loader entry through the seam: resolve + load."""
    return resolve_injector(corpus_dir, arg).load(corpus_dir)


# ---------------------------------------------------------------------------
# Molly -> trace-JSON conversion (test/benchmark fixture producer)
# ---------------------------------------------------------------------------


def _strip_ns(prov: ProvData, iteration: int, cond: str) -> dict:
    """ProvData (namespaced) -> neutral trace graph dict, inverting the
    load path's ``run_<iter>_<cond>_`` prefixing."""
    prefix = f"run_{iteration}_{cond}_"

    def bare(nid: str) -> str:
        return nid[len(prefix):] if nid.startswith(prefix) else nid

    nodes: list[dict] = []
    for g in prov.goals:
        n: dict = {"id": bare(g.id), "kind": "fact", "table": g.table,
                   "label": g.label, "time": g.time}
        if g.sender:
            n["sender"] = g.sender
        if g.receiver:
            n["receiver"] = g.receiver
        nodes.append(n)
    for r in prov.rules:
        n = {"id": bare(r.id), "kind": "rule", "table": r.table, "label": r.label}
        if r.type:
            n["rule_type"] = r.type
        nodes.append(n)
    return {
        "nodes": nodes,
        "deps": [[bare(e.src), bare(e.dst)] for e in prov.edges],
    }


def molly_to_trace(src_dir: str, dst_dir: str) -> str:
    """Convert a Molly sweep directory into the trace-JSON layout — the
    deterministic fixture producer the adapter round-trip tests and the
    non-Molly end-to-end proofs feed on.  Lossless for the analysis
    surface: statuses, failure specs, model tables (verbatim passthrough),
    message histories, and provenance graphs (namespace-stripped) survive
    the round trip bit-exactly; spacetime DOTs are dropped (the trace
    layout has none — the hazard fallback resynthesizes them from the
    messages, byte-identical for generator-produced corpora)."""
    molly = load_molly_output(src_dir)
    spec0 = molly.runs[0].failure_spec
    runs = []
    for run in molly.runs:
        fs = run.failure_spec
        entry: dict = {
            "id": run.iteration,
            "outcome": "ok" if run.succeeded else "violation",
            "faults": {
                "omissions": [
                    {"from": o.src, "to": o.dst, "at": o.time}
                    for o in (fs.omissions if fs else None) or []
                ],
                "crashes": [
                    {"node": c.node, "at": c.time}
                    for c in (fs.crashes if fs else None) or []
                ],
            },
            "history": [
                {
                    "op": "send",
                    "table": m.content,
                    "from": m.send_node,
                    "to": m.recv_node,
                    "at": m.send_time,
                    "delivered_at": m.recv_time,
                }
                for m in run.messages
            ],
            "tables": run.model.tables if run.model else {},
            "provenance": {
                "pre": _strip_ns(run.pre_prov, run.iteration, "pre"),
                "post": _strip_ns(run.post_prov, run.iteration, "post"),
            },
        }
        if run.status not in ("success", "fail"):
            entry["status"] = run.status
        runs.append(entry)
    doc = {
        "format": TRACE_FORMAT,
        "name": molly.run_name,
        "spec": {
            "eot": spec0.eot if spec0 else 0,
            "eff": spec0.eff if spec0 else 0,
            "max_crashes": spec0.max_crashes if spec0 else 0,
            "nodes": spec0.nodes if spec0 else [],
        },
        "runs": runs,
    }
    os.makedirs(dst_dir, exist_ok=True)
    with open(os.path.join(dst_dir, TRACE_FILE), "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
    return dst_dir
