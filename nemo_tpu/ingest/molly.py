"""Molly fault-injector output loader (ETL).

Reads a Molly output directory — runs.json plus per-run
run_<i>_{pre,post}_provenance.json and run_<i>_spacetime.dot — into RunData
structures, preserving the reference's ingestion invariants
(reference: faultinjectors/molly.go:15-163):

  * holds-maps are keyed by the *string* timestep in the last column of the
    model's 'pre'/'post' table rows (molly.go:38-48);
  * runs partition into success/failed on the exact status "success"
    (molly.go:52-57);
  * goals of table "clock" get their time extracted from the label via the
    two regexes `, (\\d+), __WILDCARD__\\)` and `, (\\d+), (\\d+)\\)`
    (molly.go:76-89);
  * every goal/rule/edge ID is namespaced `run_<iter>_{pre,post}_<origID>`
    (molly.go:92,101,106-107,140,149,154-155);
  * goals start with cond_holds=False until condition marking (molly.go:96).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field

from nemo_tpu import obs
from nemo_tpu.obs import log as _obs_log

from .datatypes import ProvData, RunData

_log = _obs_log.get_logger("nemo.ingest")

_CLOCK_TIME_WILD = re.compile(r", (\d+), __WILDCARD__\)")
_CLOCK_TIME_TWO = re.compile(r", (\d+), (\d+)\)")


def _fix_clock_times(prov: ProvData) -> None:
    """Extract goal timesteps for clock goals from their labels.

    Reference: faultinjectors/molly.go:72-89 (pre) / :120-137 (post).  Note the
    reference applies the two-number regex *after* the wildcard regex, so when
    both match, the two-number match wins.
    """
    for g in prov.goals:
        if g.table == "clock":
            m_wild = _CLOCK_TIME_WILD.search(g.label)
            m_two = _CLOCK_TIME_TWO.search(g.label)
            if m_wild:
                g.time = m_wild.group(1)
            if m_two:
                g.time = m_two.group(1)


def _namespace_prov(prov: ProvData, iteration: int, cond: str) -> None:
    """Prefix all IDs with run_<iter>_<cond>_ (faultinjectors/molly.go:92-107)."""
    prefix = f"run_{iteration}_{cond}_"
    for g in prov.goals:
        g.id = prefix + g.id
        g.cond_holds = False
    for r in prov.rules:
        r.id = prefix + r.id
    for e in prov.edges:
        e.src = prefix + e.src
        e.dst = prefix + e.dst


@dataclass
class MollyOutput:
    """Parsed contents of one Molly output directory.

    Mirrors the reference FaultInjector interface surface (main.go:22-30):
    runs, per-status iteration lists, failure spec, messages of failed runs.
    """

    run_name: str = ""
    output_dir: str = ""
    runs: list[RunData] = field(default_factory=list)
    runs_iters: list[int] = field(default_factory=list)
    success_runs_iters: list[int] = field(default_factory=list)
    failed_runs_iters: list[int] = field(default_factory=list)
    #: Quarantined runs (ISSUE 9): source positions whose entry or
    #: provenance files failed to parse, isolated instead of aborting the
    #: corpus (NEMO_QUARANTINE, on by default).  One record per position:
    #: {"position", "iteration" (None when the entry itself was bad),
    #: "file" (the failing file, or "runs.json"), "error"} — rendered as
    #: the report's "Degraded runs" section (quarantine.json) and carried
    #: through the corpus store so warm loads reproduce the same set.
    quarantined: list[dict] = field(default_factory=list)
    #: Whether this corpus's LAYOUT ships per-run spacetime DOT files
    #: (Molly does; the trace-JSON adapter's doesn't — ingest/adapters.py
    #: sets False).  Gates :meth:`spacetime_dot_text`'s synthesis: for a
    #: DOT-shipping layout a MISSING file stays a loud error, never a
    #: silently fabricated diagram.
    ships_spacetime_dots: bool = True

    # -- FaultInjector getters (reference: faultinjectors/molly.go:166-201) --

    def get_failure_spec(self):
        return self.runs[0].failure_spec

    def get_msgs_failed_runs(self):
        return [self.runs[i].messages for i in self.failed_runs_iters]

    def get_output(self):
        return self.runs

    def get_runs_iters(self):
        return self.runs_iters

    def get_success_runs_iters(self):
        return self.success_runs_iters

    def get_failed_runs_iters(self):
        return self.failed_runs_iters

    def spacetime_dot_path(self, iteration: int) -> str:
        """Path of Molly's space-time diagram for one run
        (reference: graphing/hazard-analysis.go:25)."""
        return os.path.join(self.output_dir, f"run_{iteration}_spacetime.dot")

    def spacetime_dot_text(self, iteration: int, run=None) -> str:
        """One run's space-time DOT text: the injector's on-disk diagram
        when the layout ships one (Molly), else synthesized
        deterministically from the run's message history and failure spec
        (models/synth.py:build_spacetime_dot — the exact builder the
        synthetic generators use, so generator-produced corpora round-trip
        byte-identically).  The synthesis keeps non-Molly front ends
        (ingest/adapters.py) figure-complete with no adapter-specific
        branch below the ingest seam: every hazard consumer reads THIS.
        Gated on ``ships_spacetime_dots`` — a Molly corpus with a
        missing/deleted DOT file still raises FileNotFoundError loudly
        instead of silently substituting a fabricated diagram.  ``run``
        skips the by-iteration scan when the caller already holds the
        RunData (the hazard loop does)."""
        if getattr(self, "ships_spacetime_dots", True):
            with open(self.spacetime_dot_path(iteration), "r", encoding="utf-8") as f:
                return f.read()
        from nemo_tpu.models.synth import build_spacetime_dot

        if run is None:
            run = next(r for r in self.runs if r.iteration == iteration)
        fs = run.failure_spec
        return build_spacetime_dot(
            list(fs.nodes or []) if fs else [],
            fs.eot if fs else 0,
            [m.to_json() for m in run.messages],
            crashes={c.node: c.time for c in (fs.crashes if fs else None) or []},
        )


def attach_run_metadata(out: MollyOutput, run, tables: dict | None = None) -> None:
    """Holds-maps + success/failure classification for one parsed run —
    shared by the object loader below and the packed-first loader
    (ingest/native.py:load_molly_output_packed) so the keying and status
    rules can never drift apart.

    Holds-maps: keyed by the string timestep in the last column of each
    'pre'/'post' model-table row (molly.go:38-48).  `tables` supplies the
    model tables directly (the packed loader passes the raw dict so run
    metadata objects stay unbuilt); default reads run.model."""
    if tables is None:
        tables = run.model.tables if run.model else {}
    run.time_pre_holds = {row[-1]: True for row in tables.get("pre", []) if row}
    run.time_post_holds = {row[-1]: True for row in tables.get("post", []) if row}
    out.runs_iters.append(run.iteration)
    if run.succeeded:
        out.success_runs_iters.append(run.iteration)
    else:
        out.failed_runs_iters.append(run.iteration)


def quarantine_record(position: int, iteration, file: str, ex: BaseException) -> dict:
    """One quarantined run's record — the single shape shared by the
    python loader, the store header, and the report's quarantine.json."""
    return {
        "position": int(position),
        "iteration": None if iteration is None else int(iteration),
        "file": file,
        "error": f"{type(ex).__name__}: {ex}",
    }


def load_molly_output(output_dir: str, quarantine: bool | None = None) -> MollyOutput:
    """Load a Molly output directory.  Reference: faultinjectors/molly.go:15-163.

    Per-run error isolation (ISSUE 9): with ``quarantine`` on (default:
    ``NEMO_QUARANTINE``, enabled), a run whose runs.json entry or
    provenance file is malformed/truncated/schema-violating is QUARANTINED
    — recorded on ``MollyOutput.quarantined`` with its parse error, counted
    as ``ingest.quarantined`` — instead of aborting the whole corpus; the
    healthy runs analyze normally.  A corpus with no healthy runs at all
    still raises (there is nothing to analyze).  runs.json itself failing
    to parse always raises: there is no per-run boundary to isolate."""
    from nemo_tpu.utils.env import quarantine_enabled

    if quarantine is None:
        quarantine = quarantine_enabled()
    out = MollyOutput(run_name=os.path.basename(os.path.normpath(output_dir)), output_dir=output_dir)

    runs_path = os.path.join(output_dir, "runs.json")
    with open(runs_path, "r", encoding="utf-8") as f:
        raw_runs = json.load(f)

    for i, raw in enumerate(raw_runs):
        try:
            run = RunData.from_json(raw)
        except Exception as ex:
            if not quarantine:
                raise
            _quarantine(out, quarantine_record(i, None, "runs.json", ex))
            continue
        try:
            # Per-run provenance files are indexed by position i, not by the
            # iteration field (molly.go:59-60).
            load_run_prov(output_dir, i, run)
        except Exception as ex:
            if not quarantine:
                raise
            cond = "post" if run.pre_prov is not None else "pre"
            _quarantine(
                out,
                quarantine_record(
                    i, run.iteration, f"run_{i}_{cond}_provenance.json", ex
                ),
            )
            continue
        out.runs.append(run)
        attach_run_metadata(out, run)

    if out.quarantined and not out.runs:
        raise RuntimeError(
            f"every run in {output_dir} failed to parse "
            f"({len(out.quarantined)} quarantined; first: "
            f"{out.quarantined[0]['error']})"
        )
    return out


def _quarantine(out: MollyOutput, rec: dict) -> None:
    out.quarantined.append(rec)
    obs.metrics.inc("ingest.quarantined")
    _log.warning(
        "ingest.quarantined",
        corpus=out.output_dir,
        position=rec["position"],
        file=rec["file"],
        error=rec["error"],
    )


def load_run_prov(output_dir: str, position: int, run) -> None:
    """Parse + namespace one run's two provenance files (indexed by file
    POSITION, not iteration — molly.go:59-60).  Split out of
    load_molly_output so chunked-ingestion producers (service/client.py)
    can parse a subset of runs per chunk, overlapping parse/pack of chunk
    k+1 with device execution of chunk k."""
    for cond, attr in (("pre", "pre_prov"), ("post", "post_prov")):
        prov_path = os.path.join(output_dir, f"run_{position}_{cond}_provenance.json")
        with open(prov_path, "r", encoding="utf-8") as f:
            prov = ProvData.from_json(json.load(f))
        _fix_clock_times(prov)
        _namespace_prov(prov, run.iteration, cond)
        setattr(run, attr, prov)
