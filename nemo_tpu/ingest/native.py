"""ctypes bindings for the native C++ ingestion engine (native/nemo_native.cpp).

The reference's ETL is compiled-native (Go, faultinjectors/molly.go); here the
hot path — Molly JSON -> packed device-ready batches — is a C++ shared library
loaded via ctypes, with the pure-Python path (ingest/molly.py +
graphs/packed.py) kept as the portable fallback and parity oracle.  The native
path produces bit-identical arrays/vocabularies to the Python path (enforced
by tests/test_native.py).

The library is compiled on demand with g++ (cached next to the source, rebuilt
when the source is newer); environments without a toolchain simply fall back.
"""

from __future__ import annotations

import ctypes
import os
from dataclasses import dataclass

import numpy as np

from nemo_tpu.ingest.datatypes import RunData
from nemo_tpu.utils.cbuild import NativeLib

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native", "nemo_native.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "..", "..", "native", "build", "libnemo_native.so")


def _bind(lib: ctypes.CDLL) -> None:
    lib.nemo_ingest.restype = ctypes.c_void_p
    lib.nemo_ingest.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.nemo_dims.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.nemo_copy.argtypes = [ctypes.c_void_p, ctypes.c_int] + [ctypes.c_void_p] * 12
    lib.nemo_runs.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.nemo_vocab.restype = ctypes.c_char_p
    lib.nemo_vocab.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.nemo_node_ids.restype = ctypes.c_char_p
    lib.nemo_node_ids.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.nemo_prov_json.restype = ctypes.c_char_p
    lib.nemo_prov_json.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.nemo_run_head_json.restype = ctypes.c_char_p
    lib.nemo_run_head_json.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.nemo_free.argtypes = [ctypes.c_void_p]


_native = NativeLib(_SRC, _LIB, _bind, "nemo_abi_version", 5)


def build_native(force: bool = False) -> str:
    """Compile the shared library if missing/stale; returns its path."""
    return _native.build(force=force)


def _load():
    return _native.load()


def native_available() -> bool:
    return _native.available


def native_error() -> str | None:
    return _native.error


@dataclass
class NativeCondBatch:
    """One condition's packed batch in the pack_batch layout ([B,V]/[B,E])."""

    table_id: np.ndarray
    label_id: np.ndarray
    time_id: np.ndarray
    type_id: np.ndarray
    is_goal: np.ndarray
    node_mask: np.ndarray
    edge_src: np.ndarray
    edge_dst: np.ndarray
    edge_mask: np.ndarray
    n_nodes: np.ndarray
    n_goals: np.ndarray
    # [B] bool: per-run @next-chain linearity verified at parse time
    # (nemo_native.cpp:graph_chain_linear) — the pointer-doubling fast-path
    # gate, so Python never re-scans the edge lists.
    chain_linear: np.ndarray


class CorpusHandle:
    """Owns one live C++ corpus handle for lazy per-run string access
    (node ids, namespaced prov JSON).  Freed on close() or GC; all array
    data is copied out eagerly, so closing only invalidates the lazy
    string accessors."""

    def __init__(self, lib, handle) -> None:
        self._lib = lib
        self._h = handle

    def prov_json(self, cond: int, run: int) -> bytes:
        if self._h is None:
            raise RuntimeError("native corpus handle already closed")
        out = self._lib.nemo_prov_json(self._h, cond, run)
        if not out:
            # Same guard as run_head_json: the C side returns "" for an
            # out-of-range row, and splicing that into debugging.json
            # would emit malformed output with no error.
            raise RuntimeError(
                f"no serialized provenance for cond {cond} run row {run} "
                "(row out of range)"
            )
        return out

    def run_head_json(self, run: int) -> bytes:
        if self._h is None:
            raise RuntimeError("native corpus handle already closed")
        out = self._lib.nemo_run_head_json(self._h, run)
        if not out:
            # The C side returns "" for an out-of-range row or a handle
            # ingested without heads; splicing that into debugging.json
            # would emit malformed output with no error (ADVICE r4 #3).
            raise RuntimeError(
                f"no head fragment for run row {run} "
                "(row out of range, or corpus ingested without heads)"
            )
        return out

    def node_ids(self, cond: int, run: int) -> list[str]:
        if self._h is None:
            raise RuntimeError("native corpus handle already closed")
        joined = self._lib.nemo_node_ids(self._h, cond, run).decode()
        return joined.split("\n") if joined else []

    def close(self) -> None:
        if self._h is not None:
            self._lib.nemo_free(self._h)
            self._h = None

    def __del__(self) -> None:  # best-effort; close() is the real contract
        try:
            self.close()
        except Exception:  # lint: allow-silent-except — __del__ must never raise; close() is the real contract
            pass


@dataclass
class NativeCorpus:
    """Full output of the native ETL for one Molly directory."""

    n_runs: int
    v: int
    e: int
    tables: list[str]
    labels: list[str]
    times: list[str]
    pre_tid: int
    post_tid: int
    max_depth: int  # corpus-wide longest DAG path bound (+1), capped at v
    iteration: np.ndarray  # [B] int32
    success: np.ndarray  # [B] bool
    pre: NativeCondBatch
    post: NativeCondBatch
    node_ids_pre: list[list[str]]
    node_ids_post: list[list[str]]
    # Live C++ handle for lazy node-id / prov-JSON access (keep_handle=True),
    # else None.
    handle: CorpusHandle | None = None

    def cond(self, name: str) -> NativeCondBatch:
        return self.pre if name == "pre" else self.post

    def prov_json(self, cond_name: str, row: int) -> bytes:
        """Byte-exact json.dumps(ProvData.to_json()) of one run's namespaced
        provenance, serialized by the C++ engine at parse time."""
        if self.handle is None:
            raise RuntimeError("corpus was ingested without keep_handle=True")
        return self.handle.prov_json(0 if cond_name == "pre" else 1, row)

    def run_head_json(self, row: int) -> bytes:
        """Canonical debugging.json head fragment of one run (iteration/
        status/failureSpec/model/messages), byte-identical to the Python
        RunData.from_json -> to_json -> json.dumps round-trip."""
        if self.handle is None:
            raise RuntimeError("corpus was ingested without keep_handle=True")
        return self.handle.run_head_json(row)

    def lazy_node_ids(self, cond_name: str, row: int) -> list[str]:
        if self.handle is None:
            ids = self.node_ids_pre if cond_name == "pre" else self.node_ids_post
            return ids[row]
        return self.handle.node_ids(0 if cond_name == "pre" else 1, row)

    @property
    def static_kwargs(self) -> dict:
        """Static kwargs for models.pipeline_model.analysis_step, identical to
        pack_molly_for_step's (power-of-two rounding included — see
        graphs_to_step: compiled-program sharing across corpora)."""
        from nemo_tpu.graphs.packed import bucket_size

        return dict(
            v=self.v,
            pre_tid=self.pre_tid,
            post_tid=self.post_tid,
            num_tables=bucket_size(len(self.tables), 8),
            num_labels=bucket_size(max(1, len(self.labels)), 8),
            max_depth=bucket_size(self.max_depth, 4),
        )


def _copy_cond(lib, handle, cond: int, b: int, v: int, e: int) -> NativeCondBatch:
    i32, u8 = np.int32, np.uint8
    arrs = dict(
        table_id=np.empty((b, v), i32),
        label_id=np.empty((b, v), i32),
        time_id=np.empty((b, v), i32),
        type_id=np.empty((b, v), i32),
        is_goal=np.empty((b, v), u8),
        node_mask=np.empty((b, v), u8),
        edge_src=np.empty((b, e), i32),
        edge_dst=np.empty((b, e), i32),
        edge_mask=np.empty((b, e), u8),
        n_nodes=np.empty((b,), i32),
        n_goals=np.empty((b,), i32),
        chain_linear=np.empty((b,), u8),
    )
    lib.nemo_copy(
        handle,
        cond,
        *(a.ctypes.data_as(ctypes.c_void_p) for a in arrs.values()),
    )
    for k in ("is_goal", "node_mask", "edge_mask", "chain_linear"):
        arrs[k] = arrs[k].astype(bool)
    return NativeCondBatch(**arrs)


def ingest_native(
    output_dir: str, with_node_ids: bool = True, keep_handle: bool = False
) -> NativeCorpus:
    """Parse + pack a Molly output directory entirely in C++.

    With keep_handle=True the C++ corpus stays alive on the returned object
    (corpus.handle) for lazy per-run node-id / prov-JSON access — the
    packed-first pipeline path fetches those strings only for the runs that
    ever need them (figure-selected + good run) and splices prov JSON into
    debugging.json at report time.

    Raises RuntimeError when the native library is unavailable (callers that
    want the fallback use `native_available()` first or catch this).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingestion unavailable: {_native.error}")
    err = ctypes.create_string_buffer(1024)
    # Head fragments are reachable only through a kept handle, so
    # keep_handle doubles as the build-heads flag.
    handle = lib.nemo_ingest(os.fsencode(output_dir), err, len(err), int(keep_handle))
    if not handle:
        raise RuntimeError(f"native ingestion failed: {err.value.decode()}")
    keeper = CorpusHandle(lib, handle)
    try:
        dims = (ctypes.c_int64 * 9)()
        lib.nemo_dims(handle, dims)
        (b, v, e, n_tables, n_labels, n_times, pre_tid, post_tid, max_depth) = (
            int(x) for x in dims
        )
        iteration = np.empty((b,), np.int32)
        success = np.empty((b,), np.uint8)
        lib.nemo_runs(
            handle,
            iteration.ctypes.data_as(ctypes.c_void_p),
            success.ctypes.data_as(ctypes.c_void_p),
        )
        tables = [lib.nemo_vocab(handle, 0, i).decode() for i in range(n_tables)]
        labels = [lib.nemo_vocab(handle, 1, i).decode() for i in range(n_labels)]
        times = [lib.nemo_vocab(handle, 2, i).decode() for i in range(n_times)]
        pre = _copy_cond(lib, handle, 0, b, v, e)
        post = _copy_cond(lib, handle, 1, b, v, e)
        ids_pre: list[list[str]] = []
        ids_post: list[list[str]] = []
        if with_node_ids:
            for i in range(b):
                ids_pre.append(keeper.node_ids(0, i))
                ids_post.append(keeper.node_ids(1, i))
        return NativeCorpus(
            n_runs=b,
            v=v,
            e=e,
            tables=tables,
            labels=labels,
            times=times,
            pre_tid=pre_tid,
            post_tid=post_tid,
            max_depth=max_depth,
            iteration=iteration,
            success=success.astype(bool),
            pre=pre,
            post=post,
            node_ids_pre=ids_pre,
            node_ids_post=ids_post,
            handle=keeper if keep_handle else None,
        )
    finally:
        if not keep_handle:
            keeper.close()


class RawProv:
    """Placeholder for one run's provenance on the packed-first ingest path:
    the parsed graph lives only as packed arrays (NativeCorpus) and the
    debugging.json serialization as a C++-held byte string; Python never
    builds the Goal/Rule/Edge object tree.  The report writer splices
    `json_str()` verbatim (analysis/pipeline.py), and the backend reads the
    arrays — nothing else may touch a RawProv (the object backends always
    ingest via the pure-Python loader)."""

    __slots__ = ("_corpus", "_cond", "_row")

    def __init__(self, corpus: NativeCorpus, cond: str, row: int) -> None:
        self._corpus = corpus
        self._cond = cond
        self._row = row

    def json_str(self) -> str:
        return self._corpus.prov_json(self._cond, self._row).decode()

    def __getattr__(self, name):  # pragma: no cover - guard rail
        raise AttributeError(
            f"RawProv has no {name!r}: packed-first ingest keeps provenance "
            "as arrays + raw JSON; use the pure-Python loader for object "
            "access (ingest/molly.py)"
        )


class LazyRunData(RunData):
    """RunData whose failureSpec/model/messages materialize from the raw
    runs.json dict only on attribute access: on the packed-first path their
    debugging.json serialization comes from the C++ head fragment
    (nemo_native.cpp:build_run_head), so for most runs the typed objects —
    the hottest Python cost at stress scale (17k runs: ~1.6 s of
    RunData.from_json + ~0.7 s of Message building per family) — are never
    constructed.  The lazy trio is parsed with the exact from_json
    normalizations, so object access (e.g. GetMsgsFailedRuns,
    faultinjectors/data-types.go:101-108 parity) sees identical values."""

    _SENTINEL = object()

    def __init__(self, raw: dict, corpus: "NativeCorpus", row: int) -> None:
        self._raw = raw
        self._lazy = {}
        self._head_row = None
        # The dataclass-generated __init__ supplies every RunData default
        # (future fields included); its writes to the lazy trio land in the
        # throwaway _lazy dict above and are re-armed to sentinels after.
        super().__init__(
            iteration=int(raw.get("iteration", 0)), status=raw.get("status", "")
        )
        self._lazy = {"failure_spec": self._SENTINEL, "model": self._SENTINEL,
                      "messages": self._SENTINEL}
        # The head fragment stays a single C++-held string (like RawProv's
        # prov bytes) and is fetched per serialization — no per-run Python
        # bytes copy of the dominant runs.json payload.
        self._head_corpus = corpus
        self._head_row = row

    @property
    def head_json(self) -> bytes | None:
        """Parse-time canonical head fragment, or None once any baked-in
        field was touched (serialization then rebuilds from the live
        objects)."""
        if self._head_row is None:
            return None
        return self._head_corpus.run_head_json(self._head_row)

    @head_json.setter
    def head_json(self, v) -> None:
        if v is not None:
            raise ValueError("head_json can only be invalidated (set to None)")
        self._head_row = None

    def _drop_head(self) -> None:
        if getattr(self, "_head_row", None) is not None:
            self._head_row = None

    def _materialize(self, name: str):
        val = self._lazy[name]
        if val is self._SENTINEL:
            from nemo_tpu.ingest.datatypes import FailureSpec, Message, Model

            d = self._raw
            if name == "failure_spec":
                val = (FailureSpec.from_json(d["failureSpec"])
                       if d.get("failureSpec") is not None else None)
            elif name == "model":
                val = Model.from_json(d["model"]) if d.get("model") is not None else None
            else:
                val = [Message.from_json(m) for m in d.get("messages") or []]
            self._lazy[name] = val
            # Once a mutable object escapes, the parse-time head can go
            # stale through in-place mutation (run.messages.append(...)) —
            # drop it so serialization rebuilds from the live objects.  The
            # standard pipeline never touches the trio on this path, so the
            # splice survives for every untouched run.
            self._drop_head()
        return val

    def _assign(self, name: str, v) -> None:
        self._lazy[name] = v
        # A mutated trio invalidates the parse-time head fragment: the next
        # serialization must rebuild from the (new) objects, not splice
        # stale bytes.
        self._drop_head()

    def _plain_guarded(name: str):
        # iteration/status are baked into the head like the lazy trio;
        # reassigning either must drop the parse-time bytes too.
        def setter(self, v):
            self.__dict__[name] = v
            self._drop_head()

        return property(lambda self: self.__dict__[name], setter)

    # Data descriptors take precedence over instance attributes, so these
    # stay authoritative even though RunData is a plain dataclass.
    failure_spec = property(lambda self: self._materialize("failure_spec"),
                            lambda self, v: self._assign("failure_spec", v))
    model = property(lambda self: self._materialize("model"),
                     lambda self, v: self._assign("model", v))
    messages = property(lambda self: self._materialize("messages"),
                        lambda self, v: self._assign("messages", v))
    iteration = _plain_guarded("iteration")
    status = _plain_guarded("status")
    del _plain_guarded

    @property
    def holds_tables(self) -> dict:
        """Just the 'pre'/'post' model tables with Model.from_json's
        list(r) row normalization applied — exactly what
        attach_run_metadata reads for the holds maps — without building
        Model objects for the (potentially large) remaining tables."""
        tables = (self._raw.get("model") or {}).get("tables") or {}
        return {
            k: [list(r) for r in tables[k]] for k in ("pre", "post") if k in tables
        }


def load_molly_output_packed(output_dir: str):
    """Packed-first Molly ingest: run metadata via the Python loader's
    runs.json semantics, all 2N provenance files via the C++ engine — no
    per-goal Python objects are ever built (VERDICT r3 task 1: the CLI
    pipeline's ingest was ~flat-profile Python at stress scale), and since
    r4 no per-run metadata objects either: the C++ engine serializes each
    run's debugging.json head fragment at parse time and RunData fields
    materialize lazily from the raw dict only if something reads them.

    Returns a MollyOutput whose runs carry RawProv placeholders and which
    exposes the packed arrays as `.native_corpus` for the JaxBackend's
    zero-repack init path."""
    import json

    from nemo_tpu.ingest import molly
    from nemo_tpu.ingest.molly import MollyOutput

    corpus = ingest_native(output_dir, with_node_ids=False, keep_handle=True)
    out = MollyOutput(
        run_name=os.path.basename(os.path.normpath(output_dir)), output_dir=output_dir
    )
    with open(os.path.join(output_dir, "runs.json"), "r", encoding="utf-8") as f:
        raw_runs = json.load(f)
    if len(raw_runs) != corpus.n_runs:
        raise RuntimeError(
            f"native corpus has {corpus.n_runs} runs but runs.json has {len(raw_runs)}"
        )
    out.runs = [LazyRunData(r, corpus, i) for i, r in enumerate(raw_runs)]
    for i, run in enumerate(out.runs):
        molly.attach_run_metadata(out, run, tables=run.holds_tables)
        run.pre_prov = RawProv(corpus, "pre", i)
        run.post_prov = RawProv(corpus, "post", i)
    out.native_corpus = corpus
    return out


def corpus_step_static(c) -> dict:
    """analysis_step statics for a whole packed corpus: the shared
    `static_kwargs` plus the corpus-level comp_linear flag (AND over the
    per-graph parse-time checks) — the ONE derivation used by
    pack_molly_dir_host and the sidecar's AnalyzeDir handler."""
    lin = bool(
        np.asarray(c.pre.chain_linear).all() and np.asarray(c.post.chain_linear).all()
    )
    return dict(c.static_kwargs, comp_linear=lin)


def packed_host_available(output_dir: str) -> bool:
    """Can pack_molly_dir_host serve this directory?  Yes when the native
    engine builds, or when the corpus store holds a warm hit for it — the
    mmap load needs no C++ at all, so lib-less client paths
    (analyze_dir/analyze_dir_pipelined) still get packed ingest whenever
    the store can serve."""
    if native_available():
        return True
    from nemo_tpu.store import resolve_store

    store = resolve_store()
    # "grown" qualifies too: load_corpus appends the new runs first (store
    # maintenance) and then serves warm — the incremental-sweep scenario.
    return store is not None and store.probe(output_dir) in ("hit", "grown")


def pack_molly_dir_host(output_dir: str, timings: dict | None = None):
    """Directory -> (NativeCorpus, static kwargs): the native ETL's host-side
    product — numpy batch arrays plus the analysis_step statics (including
    the host-verified comp_linear flag) — with NO device transfer.  The
    sidecar's chunk producers slice these rows straight into protobufs;
    pack_molly_dir wraps them in device BatchArrays for in-process use.
    When `timings` is given, "linear_check_s" records the residual host
    cost of deriving the corpus flag — a trivial AND over the per-graph
    flags the C++ engine verified during parse (graph_chain_linear), so a
    near-zero reading means the check's real work rode the parse pass, not
    that it disappeared.  Either way nothing touches the device.

    The persistent corpus store (nemo_tpu/store, NEMO_CORPUS_CACHE) is
    consulted FIRST via its corpus-only load: a warm hit serves the same
    corpus arrays by mmap with zero per-run Python work — the
    analyze_dir/analyze_dir_pipelined client paths share the pipeline's
    warm ingest.  This path never POPULATES a cold store (it drops the
    per-run strings a full store needs; the report pipeline and the
    sidecar's AnalyzeDir handler are the populating producers), though a
    GROWN directory is appended to first — load-side store maintenance,
    which takes that store's writer lock for the tail parse.  A miss
    parses natively as before."""
    import time

    from nemo_tpu.store import resolve_store

    store = resolve_store()
    if store is not None:
        c = store.load_corpus(output_dir)
        if c is not None:
            if timings is not None:
                timings["linear_check_s"] = 0.0
            return c, corpus_step_static(c)

    if not native_available():
        # Reachable when a probed store hit went stale/corrupt between the
        # packed_host_available() check and here: fail with the remedy
        # instead of deep inside ingest_native.
        raise RuntimeError(
            f"native ingestion unavailable ({native_error()}) and no warm "
            f"corpus store for {output_dir}; use the pure-Python loader "
            "(pack_molly_for_step) or populate the store"
        )
    c = ingest_native(output_dir, with_node_ids=False)
    t0 = time.perf_counter()
    # Per-graph linearity was verified by the C++ engine at parse time
    # (graph_chain_linear, mirroring ops/simplify.py:chains_linear_host);
    # the corpus-level flag is just the AND over both conditions.
    static = corpus_step_static(c)
    if timings is not None:
        timings["linear_check_s"] = time.perf_counter() - t0
    return c, static


def pack_molly_dir(output_dir: str, timings: dict | None = None):
    """Directory -> (pre BatchArrays, post BatchArrays, static kwargs) for
    models.pipeline_model.analysis_step, via the host path when it can
    serve (native engine OR a warm corpus-store hit — lib-less hosts
    included) and the pure-Python path otherwise.  `timings` passes through
    to pack_molly_dir_host (no-op on the Python fallback, where the
    linearity check runs inside pack_molly_for_step)."""
    if packed_host_available(output_dir):
        from nemo_tpu.models.pipeline_model import BatchArrays

        c, static = pack_molly_dir_host(output_dir, timings=timings)
        return (
            BatchArrays.from_packed(c.pre),
            BatchArrays.from_packed(c.post),
            static,
        )
    from nemo_tpu.ingest.molly import load_molly_output
    from nemo_tpu.models.pipeline_model import pack_molly_for_step

    return pack_molly_for_step(load_molly_output(output_dir))
