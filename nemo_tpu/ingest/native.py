"""ctypes bindings for the native C++ ingestion engine (native/nemo_native.cpp).

The reference's ETL is compiled-native (Go, faultinjectors/molly.go); here the
hot path — Molly JSON -> packed device-ready batches — is a C++ shared library
loaded via ctypes, with the pure-Python path (ingest/molly.py +
graphs/packed.py) kept as the portable fallback and parity oracle.  The native
path produces bit-identical arrays/vocabularies to the Python path (enforced
by tests/test_native.py).

The library is compiled on demand with g++ (cached next to the source, rebuilt
when the source is newer); environments without a toolchain simply fall back.
"""

from __future__ import annotations

import ctypes
import os
from dataclasses import dataclass

import numpy as np

from nemo_tpu.utils.cbuild import NativeLib

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native", "nemo_native.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "..", "..", "native", "build", "libnemo_native.so")


def _bind(lib: ctypes.CDLL) -> None:
    lib.nemo_ingest.restype = ctypes.c_void_p
    lib.nemo_ingest.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.nemo_dims.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.nemo_copy.argtypes = [ctypes.c_void_p, ctypes.c_int] + [ctypes.c_void_p] * 11
    lib.nemo_runs.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.nemo_vocab.restype = ctypes.c_char_p
    lib.nemo_vocab.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.nemo_node_ids.restype = ctypes.c_char_p
    lib.nemo_node_ids.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.nemo_free.argtypes = [ctypes.c_void_p]


_native = NativeLib(_SRC, _LIB, _bind, "nemo_abi_version", 2)


def build_native(force: bool = False) -> str:
    """Compile the shared library if missing/stale; returns its path."""
    return _native.build(force=force)


def _load():
    return _native.load()


def native_available() -> bool:
    return _native.available


def native_error() -> str | None:
    return _native.error


@dataclass
class NativeCondBatch:
    """One condition's packed batch in the pack_batch layout ([B,V]/[B,E])."""

    table_id: np.ndarray
    label_id: np.ndarray
    time_id: np.ndarray
    type_id: np.ndarray
    is_goal: np.ndarray
    node_mask: np.ndarray
    edge_src: np.ndarray
    edge_dst: np.ndarray
    edge_mask: np.ndarray
    n_nodes: np.ndarray
    n_goals: np.ndarray


@dataclass
class NativeCorpus:
    """Full output of the native ETL for one Molly directory."""

    n_runs: int
    v: int
    e: int
    tables: list[str]
    labels: list[str]
    times: list[str]
    pre_tid: int
    post_tid: int
    max_depth: int  # corpus-wide longest DAG path bound (+1), capped at v
    iteration: np.ndarray  # [B] int32
    success: np.ndarray  # [B] bool
    pre: NativeCondBatch
    post: NativeCondBatch
    node_ids_pre: list[list[str]]
    node_ids_post: list[list[str]]

    @property
    def static_kwargs(self) -> dict:
        """Static kwargs for models.pipeline_model.analysis_step, identical to
        pack_molly_for_step's (power-of-two rounding included — see
        graphs_to_step: compiled-program sharing across corpora)."""
        from nemo_tpu.graphs.packed import bucket_size

        return dict(
            v=self.v,
            pre_tid=self.pre_tid,
            post_tid=self.post_tid,
            num_tables=bucket_size(len(self.tables), 8),
            num_labels=bucket_size(max(1, len(self.labels)), 8),
            max_depth=bucket_size(self.max_depth, 4),
        )


def _copy_cond(lib, handle, cond: int, b: int, v: int, e: int) -> NativeCondBatch:
    i32, u8 = np.int32, np.uint8
    arrs = dict(
        table_id=np.empty((b, v), i32),
        label_id=np.empty((b, v), i32),
        time_id=np.empty((b, v), i32),
        type_id=np.empty((b, v), i32),
        is_goal=np.empty((b, v), u8),
        node_mask=np.empty((b, v), u8),
        edge_src=np.empty((b, e), i32),
        edge_dst=np.empty((b, e), i32),
        edge_mask=np.empty((b, e), u8),
        n_nodes=np.empty((b,), i32),
        n_goals=np.empty((b,), i32),
    )
    lib.nemo_copy(
        handle,
        cond,
        *(a.ctypes.data_as(ctypes.c_void_p) for a in arrs.values()),
    )
    for k in ("is_goal", "node_mask", "edge_mask"):
        arrs[k] = arrs[k].astype(bool)
    return NativeCondBatch(**arrs)


def ingest_native(output_dir: str, with_node_ids: bool = True) -> NativeCorpus:
    """Parse + pack a Molly output directory entirely in C++.

    Raises RuntimeError when the native library is unavailable (callers that
    want the fallback use `native_available()` first or catch this).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingestion unavailable: {_native.error}")
    err = ctypes.create_string_buffer(1024)
    handle = lib.nemo_ingest(os.fsencode(output_dir), err, len(err))
    if not handle:
        raise RuntimeError(f"native ingestion failed: {err.value.decode()}")
    try:
        dims = (ctypes.c_int64 * 9)()
        lib.nemo_dims(handle, dims)
        (b, v, e, n_tables, n_labels, n_times, pre_tid, post_tid, max_depth) = (
            int(x) for x in dims
        )
        iteration = np.empty((b,), np.int32)
        success = np.empty((b,), np.uint8)
        lib.nemo_runs(
            handle,
            iteration.ctypes.data_as(ctypes.c_void_p),
            success.ctypes.data_as(ctypes.c_void_p),
        )
        tables = [lib.nemo_vocab(handle, 0, i).decode() for i in range(n_tables)]
        labels = [lib.nemo_vocab(handle, 1, i).decode() for i in range(n_labels)]
        times = [lib.nemo_vocab(handle, 2, i).decode() for i in range(n_times)]
        pre = _copy_cond(lib, handle, 0, b, v, e)
        post = _copy_cond(lib, handle, 1, b, v, e)
        ids_pre: list[list[str]] = []
        ids_post: list[list[str]] = []
        if with_node_ids:
            for i in range(b):
                joined_pre = lib.nemo_node_ids(handle, 0, i).decode()
                joined_post = lib.nemo_node_ids(handle, 1, i).decode()
                ids_pre.append(joined_pre.split("\n") if joined_pre else [])
                ids_post.append(joined_post.split("\n") if joined_post else [])
        return NativeCorpus(
            n_runs=b,
            v=v,
            e=e,
            tables=tables,
            labels=labels,
            times=times,
            pre_tid=pre_tid,
            post_tid=post_tid,
            max_depth=max_depth,
            iteration=iteration,
            success=success.astype(bool),
            pre=pre,
            post=post,
            node_ids_pre=ids_pre,
            node_ids_post=ids_post,
        )
    finally:
        lib.nemo_free(handle)


def pack_molly_dir(output_dir: str):
    """Directory -> (pre BatchArrays, post BatchArrays, static kwargs) for
    models.pipeline_model.analysis_step, via the native engine when available
    and the Python path otherwise."""
    if native_available():
        c = ingest_native(output_dir, with_node_ids=False)
        from nemo_tpu.models.pipeline_model import BatchArrays

        # NativeCondBatch exposes the same field names as PackedBatch, so the
        # shared constructor applies.
        return (
            BatchArrays.from_packed(c.pre),
            BatchArrays.from_packed(c.post),
            c.static_kwargs,
        )
    from nemo_tpu.ingest.molly import load_molly_output
    from nemo_tpu.models.pipeline_model import pack_molly_for_step

    return pack_molly_for_step(load_molly_output(output_dir))
