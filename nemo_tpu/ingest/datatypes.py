"""Core data model for fault-injector output.

JSON field names match the Molly output schema consumed by the reference
(reference: faultinjectors/data-types.go:6-98), so that the same Molly output
directories — and the same debugging.json report contract — work unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class CrashFailure:
    """A node crash injected by the fault injector.

    Reference: faultinjectors/data-types.go:6-9.
    """

    node: str = ""
    time: int = 0

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "CrashFailure":
        return cls(node=d.get("node", ""), time=int(d.get("time", 0)))

    def to_json(self) -> dict[str, Any]:
        return {"node": self.node, "time": self.time}


@dataclass
class MessageLoss:
    """A message omission injected by the fault injector.

    Reference: faultinjectors/data-types.go:12-16.
    """

    src: str = ""
    dst: str = ""
    time: int = 0

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "MessageLoss":
        return cls(src=d.get("from", ""), dst=d.get("to", ""), time=int(d.get("time", 0)))

    def to_json(self) -> dict[str, Any]:
        return {"from": self.src, "to": self.dst, "time": self.time}


@dataclass
class FailureSpec:
    """Bounds and concrete faults of one fault-injection execution.

    Reference: faultinjectors/data-types.go:19-26.
    """

    eot: int = 0
    eff: int = 0
    max_crashes: int = 0
    nodes: list[str] | None = None
    crashes: list[CrashFailure] | None = None
    omissions: list[MessageLoss] | None = None

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "FailureSpec":
        return cls(
            eot=int(d.get("eot", 0)),
            eff=int(d.get("eff", 0)),
            max_crashes=int(d.get("maxCrashes", 0)),
            nodes=list(d["nodes"]) if d.get("nodes") is not None else None,
            crashes=[CrashFailure.from_json(c) for c in d["crashes"]]
            if d.get("crashes") is not None
            else None,
            omissions=[MessageLoss.from_json(o) for o in d["omissions"]]
            if d.get("omissions") is not None
            else None,
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "eot": self.eot,
            "eff": self.eff,
            "maxCrashes": self.max_crashes,
            "nodes": self.nodes,
            "crashes": [c.to_json() for c in self.crashes] if self.crashes is not None else None,
            "omissions": [o.to_json() for o in self.omissions]
            if self.omissions is not None
            else None,
        }


@dataclass
class Model:
    """Final database state of one run: table name -> rows of strings.

    Reference: faultinjectors/data-types.go:29-31.  The last column of each row
    of tables 'pre'/'post' is the timestep at which the condition held
    (faultinjectors/molly.go:38-48).
    """

    tables: dict[str, list[list[str]]] = field(default_factory=dict)

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Model":
        return cls(tables={k: [list(r) for r in v] for k, v in d.get("tables", {}).items()})

    def to_json(self) -> dict[str, Any]:
        return {"tables": self.tables}


@dataclass
class Message:
    """One message observed during a run.

    Reference: faultinjectors/data-types.go:34-40.
    """

    content: str = ""
    send_node: str = ""
    recv_node: str = ""
    send_time: int = 0
    recv_time: int = 0

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Message":
        return cls(
            content=d.get("table", ""),
            send_node=d.get("from", ""),
            recv_node=d.get("to", ""),
            send_time=int(d.get("sendTime", 0)),
            recv_time=int(d.get("receiveTime", 0)),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "table": self.content,
            "from": self.send_node,
            "to": self.recv_node,
            "sendTime": self.send_time,
            "receiveTime": self.recv_time,
        }


@dataclass
class Goal:
    """A derived fact (tuple) in a provenance graph.

    Reference: faultinjectors/data-types.go:43-51.
    """

    id: str = ""
    label: str = ""
    table: str = ""
    time: str = ""
    cond_holds: bool = False
    sender: str = ""
    receiver: str = ""

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Goal":
        return cls(
            id=d.get("id", ""),
            label=d.get("label", ""),
            table=d.get("table", ""),
            time=str(d.get("time", "")),
            cond_holds=bool(d.get("conditionHolds", False)),
            sender=d.get("sender", ""),
            receiver=d.get("receiver", ""),
        )

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "id": self.id,
            "label": self.label,
            "table": self.table,
            "time": self.time,
        }
        if self.cond_holds:
            out["conditionHolds"] = self.cond_holds
        if self.sender:
            out["sender"] = self.sender
        if self.receiver:
            out["receiver"] = self.receiver
        return out


@dataclass
class Rule:
    """A rule firing in a provenance graph.

    Reference: faultinjectors/data-types.go:54-59.  type is one of
    "" (deductive), "async" (network), "next" (timer/persistence), plus the
    synthetic "collapsed" type produced by chain contraction
    (graphing/preprocessing.go:279).
    """

    id: str = ""
    label: str = ""
    table: str = ""
    type: str = ""

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Rule":
        return cls(
            id=d.get("id", ""),
            label=d.get("label", ""),
            table=d.get("table", ""),
            type=d.get("type", ""),
        )

    def to_json(self) -> dict[str, Any]:
        return {"id": self.id, "label": self.label, "table": self.table, "type": self.type}


@dataclass
class Edge:
    """A directed provenance edge (goal->rule or rule->goal).

    Reference: faultinjectors/data-types.go:62-65.
    """

    src: str = ""
    dst: str = ""

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Edge":
        return cls(src=d.get("from", ""), dst=d.get("to", ""))

    def to_json(self) -> dict[str, Any]:
        return {"from": self.src, "to": self.dst}


@dataclass
class ProvData:
    """One provenance graph: goals, rules, and directed edges.

    Reference: faultinjectors/data-types.go:68-72.
    """

    goals: list[Goal] = field(default_factory=list)
    rules: list[Rule] = field(default_factory=list)
    edges: list[Edge] = field(default_factory=list)

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "ProvData":
        return cls(
            goals=[Goal.from_json(g) for g in d.get("goals", [])],
            rules=[Rule.from_json(r) for r in d.get("rules", [])],
            edges=[Edge.from_json(e) for e in d.get("edges", [])],
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "goals": [g.to_json() for g in self.goals],
            "rules": [r.to_json() for r in self.rules],
            "edges": [e.to_json() for e in self.edges],
        }


@dataclass
class MissingEvent:
    """A frontier rule of the differential-provenance graph together with the
    goals it would have derived — the events whose absence (transitively)
    explains the invariant violation.

    Reference: faultinjectors/data-types.go:75-78.  The Go struct has no JSON
    tags, so Go marshals it with capitalized field names ("Rule", "Goals");
    we keep that for debugging.json report parity.
    """

    rule: Rule | None = None
    goals: list[Goal] = field(default_factory=list)

    def to_json(self) -> dict[str, Any]:
        return {
            "Rule": self.rule.to_json() if self.rule is not None else None,
            "Goals": [g.to_json() for g in self.goals],
        }


@dataclass
class RunData:
    """Everything known about one fault-injection run.

    Reference: faultinjectors/data-types.go:81-98 ('Run').
    """

    iteration: int = 0
    status: str = ""
    failure_spec: FailureSpec | None = None
    model: Model | None = None
    messages: list[Message] = field(default_factory=list)
    pre_prov: ProvData | None = None
    time_pre_holds: dict[str, bool] = field(default_factory=dict)
    post_prov: ProvData | None = None
    time_post_holds: dict[str, bool] = field(default_factory=dict)
    recommendation: list[str] = field(default_factory=list)
    corrections: list[str] = field(default_factory=list)
    missing_events: list[MissingEvent] = field(default_factory=list)
    inter_proto: list[str] = field(default_factory=list)
    inter_proto_missing: list[str] = field(default_factory=list)
    union_proto: list[str] = field(default_factory=list)
    union_proto_missing: list[str] = field(default_factory=list)

    @property
    def succeeded(self) -> bool:
        # Success is the exact string "success" (faultinjectors/molly.go:53).
        return self.status == "success"

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "RunData":
        return cls(
            iteration=int(d.get("iteration", 0)),
            status=d.get("status", ""),
            failure_spec=FailureSpec.from_json(d["failureSpec"])
            if d.get("failureSpec") is not None
            else None,
            model=Model.from_json(d["model"]) if d.get("model") is not None else None,
            messages=[Message.from_json(m) for m in d.get("messages") or []],
        )

    def to_json(self) -> dict[str, Any]:
        """Serialize in the debugging.json schema the report frontend reads.

        Mirrors Go's encoding/json output for the reference Run struct
        (faultinjectors/data-types.go:81-98): omitempty fields are dropped
        when empty.
        """
        out: dict[str, Any] = {
            "iteration": self.iteration,
            "status": self.status,
            "failureSpec": self.failure_spec.to_json() if self.failure_spec else None,
            "model": self.model.to_json() if self.model else None,
            "messages": [m.to_json() for m in self.messages],
        }
        if self.pre_prov is not None:
            out["preProv"] = self.pre_prov.to_json()
        if self.time_pre_holds:
            out["timePreHolds"] = self.time_pre_holds
        if self.post_prov is not None:
            out["postProv"] = self.post_prov.to_json()
        if self.time_post_holds:
            out["timePostHolds"] = self.time_post_holds
        if self.recommendation:
            out["recommendation"] = self.recommendation
        if self.corrections:
            out["corrections"] = self.corrections
        if self.missing_events:
            out["missingEvents"] = [m.to_json() for m in self.missing_events]
        if self.inter_proto:
            out["interProto"] = self.inter_proto
        if self.inter_proto_missing:
            out["interProtoMissing"] = self.inter_proto_missing
        if self.union_proto:
            out["unionProto"] = self.union_proto
        if self.union_proto_missing:
            out["unionProtoMissing"] = self.union_proto_missing
        return out
