"""nemo-tpu command-line interface.

CLI parity with the reference binary (main.go:68-78): `-faultInjOut` (required
path to the fault injector's output directory) and `-graphDBConn` (accepted
for compatibility; only meaningful to external-store backends).  Grows the
`--graph-backend={python,jax}` selector the north star prescribes
(SURVEY.md §0): `python` is the in-process oracle baseline, `jax` the
batched TPU backend.

Usage:
    python -m nemo_tpu.cli -faultInjOut <dir> [--graph-backend=jax]
"""

from __future__ import annotations

import argparse
import os
import sys

from nemo_tpu.analysis.pipeline import run_debug, run_debug_dirs
from nemo_tpu.obs import trace as obs_trace
from nemo_tpu.utils.jax_config import (
    PlatformUnavailableError,
    enable_compilation_cache,
    ensure_platform,
    pin_platform,
)


def make_backend(name: str):
    if name == "python":
        from nemo_tpu.backend.python_ref import PythonBackend

        return PythonBackend()
    if name == "jax":
        from nemo_tpu.backend.jax_backend import JaxBackend

        return JaxBackend()
    if name == "neo4j":
        from nemo_tpu.backend.neo4j_backend import Neo4jBackend

        return Neo4jBackend()
    if name == "service":
        from nemo_tpu.backend.service_backend import ServiceBackend

        return ServiceBackend()
    raise SystemExit(
        f"unknown graph backend: {name!r} (expected python, jax, neo4j, or service)"
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="nemo-tpu", description="Provenance-graph debugging of distributed protocols."
    )
    # Single-dash long options for reference CLI parity (Go flag style).
    parser.add_argument(
        "-faultInjOut",
        "--fault-inj-out",
        dest="fault_inj_out",
        required=True,
        action="append",
        help="file system path to output directory of fault injector.  "
        "Repeatable: several corpus directories analyze in ONE run through "
        "the overlapped multi-corpus driver (corpus k+1's ingest and the "
        "figure pipeline ride under corpus k's analysis), one report per "
        "directory under --results-dir",
    )
    parser.add_argument(
        "-graphDBConn",
        "--graph-db-conn",
        dest="graph_db_conn",
        default="bolt://127.0.0.1:7687",
        help="connection URI for external graph-database backends (unused by "
        "the in-process backends)",
    )
    parser.add_argument(
        "--graph-backend",
        choices=("python", "jax", "neo4j", "service"),
        default="python",
        help="graph analytics engine: in-process Python oracle, batched "
        "JAX/TPU, a Neo4j server at -graphDBConn (the reference's backend), "
        "or the gRPC TPU sidecar at -graphDBConn (host:port; start it with "
        "python -m nemo_tpu.service.server)",
    )
    parser.add_argument(
        "--results-dir",
        default=os.path.join(os.getcwd(), "results"),
        help="root directory for generated reports (default ./results)",
    )
    parser.add_argument(
        "--timings", action="store_true", help="print per-phase wall-clock timings"
    )
    parser.add_argument(
        "--serve",
        metavar="PORT",
        type=int,
        default=0,
        help="after generating the report, serve it on http://127.0.0.1:PORT "
        "(browsers block fetch() on file:// URLs, so the report's "
        "debugging.json load needs an HTTP origin)",
    )
    parser.add_argument(
        "--profile",
        metavar="DIR",
        default=None,
        help="capture a jax.profiler trace of the analysis phases into DIR "
        "(view with TensorBoard/xprof)",
    )
    parser.add_argument(
        "--trace",
        metavar="FILE",
        default=None,
        help="write a Chrome-trace-event JSON of host spans (pipeline "
        "phases, kernel dispatches, render workers, RPC client+server) to "
        "FILE — open it at ui.perfetto.dev.  Equivalent env: NEMO_TRACE.  "
        "Near-zero overhead when off",
    )
    parser.add_argument(
        "--metrics-out",
        metavar="FILE",
        default=None,
        help="after the run, dump the obs metrics registry one-shot in "
        "Prometheus text format to FILE ('-' for stdout) — the same "
        "rendering the sidecar serves on --metrics-port",
    )
    parser.add_argument(
        "--figures",
        default="all",
        metavar="POLICY",
        help="figure materialization policy: 'all' (reference behavior), "
        "'failed' (failed runs + the good baseline run), 'sample:N' "
        "(failed + good + N sampled runs), or 'none'.  debugging.json "
        "always covers every run; at 10k+ run scale rendering every "
        "figure dominates wall clock",
    )
    parser.add_argument(
        "--render-workers",
        type=int,
        default=None,
        metavar="N",
        help="figure-render worker processes (default $NEMO_RENDER_WORKERS "
        "or cpu count; 1 renders inline).  Unique figures only: figures "
        "are deduplicated by render content and served from the "
        "persistent SVG cache before any worker runs",
    )
    parser.add_argument(
        "--svg-cache",
        default=None,
        metavar="DIR",
        help="persistent SVG cache directory (default $NEMO_SVG_CACHE or "
        "~/.cache/nemo_tpu/svg; 'off' disables).  Keyed by (render "
        "content hash, renderer version), so warm re-reports skip "
        "rendering entirely",
    )
    parser.add_argument(
        "--platform",
        default=None,
        metavar="NAME",
        help="jax platform: 'auto' (probe the device under a watchdog, fall "
        "back to CPU if unreachable — the environment's TPU tunnel HANGS "
        "device discovery during outages), 'cpu', 'tpu', or a concrete "
        "platform name (default: $NEMO_PLATFORM or auto)",
    )
    parser.add_argument(
        "--save-corpus",
        metavar="PATH",
        default=None,
        help="after ingestion, persist the packed-array corpus as a .npz "
        "bundle (graphs/corpus.py) so analysis can be re-run without "
        "re-parsing the Molly output",
    )
    parser.add_argument(
        "--corpus-cache",
        default=None,
        metavar="DIR|off",
        help="persistent memory-mapped corpus store root (default "
        "$NEMO_CORPUS_CACHE or ~/.cache/nemo_tpu/corpus; 'off' disables).  "
        "The packed ingest path parses each Molly directory ONCE and then "
        "mmap-loads the packed arrays in milliseconds; growing directories "
        "are appended to incrementally, and any mismatch (fingerprint, "
        "version, checksum) falls back loudly to the parse path",
    )
    parser.add_argument(
        "--result-cache",
        default=None,
        metavar="DIR|off",
        help="content-addressed analysis result cache root (default "
        "$NEMO_RESULT_CACHE or ~/.cache/nemo_tpu/results; 'off' disables).  "
        "Keyed by (corpus store segment fingerprints, figure policy, "
        "kernel/report ABI): a repeat request over an unchanged corpus "
        "restores the full report with zero kernel dispatches, and a "
        "grown corpus re-analyzes only its new runs, merging cached "
        "per-segment partials (analysis/delta.py).  Requires the corpus "
        "store (--corpus-cache) — without store fingerprints nothing "
        "content-addresses the corpus, so every request recomputes",
    )
    parser.add_argument(
        "--ingest",
        default="auto",
        choices=("auto", "native", "python"),
        help="ETL selection: 'native' parses+packs all provenance in the "
        "C++ engine (array backends only), 'python' builds the object "
        "tree, 'auto' (default) picks native when the backend supports "
        "packed ingest and the library builds",
    )
    args = parser.parse_args(argv)

    dirs = args.fault_inj_out
    for d in dirs:
        if not os.path.isdir(d):
            parser.error(f"fault injector output directory not found: {d}")
    if len(dirs) > 1 and args.save_corpus:
        parser.error(
            "--save-corpus is incompatible with multiple -faultInjOut "
            "directories (every corpus would overwrite the same bundle); "
            "run per directory with distinct paths"
        )

    # Tracing: the flag wins, NEMO_TRACE is the env equivalent.  The trace
    # is written explicitly before the final prints below (so the path is
    # announced), with an atexit backstop for crash paths.  The env is NOT
    # mutated: main() may run many times in one process (tests).
    if args.trace:
        import atexit

        obs_trace.start_trace(args.trace)
        atexit.register(obs_trace.finish)
    else:
        obs_trace.configure_from_env()

    if args.graph_backend == "jax":
        # The only backend that touches the accelerator in-process; resolve
        # the platform under a watchdog so a tunnel outage degrades to CPU
        # instead of hanging (the reference CLI always terminates,
        # main.go:65-292 — every error is log.Fatalf).
        try:
            platform = ensure_platform(args.platform)
        except PlatformUnavailableError as e:
            # Explicit --platform=tpu with no reachable device: terminate
            # nonzero (log.Fatalf semantics) rather than silently degrading.
            print(f"fatal: {e}", file=sys.stderr)
            return 2
        print(f"jax platform: {platform}", file=sys.stderr)
    else:
        # python/neo4j run no device code; the service backend's device
        # lives in the sidecar process.  Pin CPU unless the user explicitly
        # asked otherwise, so stray jax imports can't block on tunnel health.
        pin_platform(args.platform if args.platform not in (None, "", "auto") else "cpu")
    enable_compilation_cache()
    # The render knobs travel as env so the resolution is identical across
    # the CLI, the bench, and run_debug_dirs (report/render.py reads them).
    if args.render_workers is not None:
        os.environ["NEMO_RENDER_WORKERS"] = str(args.render_workers)
    if args.svg_cache is not None:
        os.environ["NEMO_SVG_CACHE"] = args.svg_cache
    if args.corpus_cache is not None:
        os.environ["NEMO_CORPUS_CACHE"] = args.corpus_cache
    if args.result_cache is not None:
        os.environ["NEMO_RESULT_CACHE"] = args.result_cache
    # The tracer is finished in the finally: a pipeline failure must still
    # write the partial trace (a trace of a failed run is exactly when you
    # want one) AND disable the global tracer — main() may run again in
    # this process, and a stale enabled tracer would silently swallow the
    # next run's spans into the old file.
    try:
        if len(dirs) == 1:
            result = run_debug(
                dirs[0],
                args.results_dir,
                make_backend(args.graph_backend),
                conn=args.graph_db_conn,
                save_corpus_path=args.save_corpus,
                profile_dir=args.profile,
                figures=args.figures,
                ingest=args.ingest,
            )
            results = [result]
        else:
            results = run_debug_dirs(
                dirs,
                args.results_dir,
                lambda: make_backend(args.graph_backend),
                conn=args.graph_db_conn,
                profile_dir=args.profile,
                figures=args.figures,
                ingest=args.ingest,
            )
            result = results[-1]
    except BaseException:
        trace_path = obs_trace.finish()
        if trace_path:
            print(
                f"obs trace (partial, run failed) written to {trace_path}",
                file=sys.stderr,
            )
        raise

    if args.timings:
        for res in results:
            if len(results) > 1:
                print(f"--- {res.molly.run_name}")
            for phase, secs in res.timings.items():
                print(f"{phase:>22s}  {secs * 1e3:9.1f} ms")
        fs = result.figure_stats
        if fs and fs.get("figures"):
            print(
                f"figures: {fs['figures']} rendered as {fs['unique_figures']} "
                f"unique (dedup {fs['dedup_ratio']}x), "
                f"{fs['figure_cache_hits']} cache hits, "
                f"{fs['render_workers']} render workers"
            )

    trace_path = obs_trace.finish()
    if trace_path:
        print(f"obs trace written to {trace_path} (open at ui.perfetto.dev)")

    if args.metrics_out:
        from nemo_tpu.obs import promexp

        text = promexp.render_prometheus()
        if args.metrics_out == "-":
            sys.stdout.write(text)
        else:
            with open(args.metrics_out, "w", encoding="utf-8") as fh:
                fh.write(text)
            print(f"metrics written to {args.metrics_out} (Prometheus text format)")

    for res in results:
        print(f"All done! Find the debug report here: {os.path.join(res.report_dir, 'index.html')}")

    if args.serve:
        import functools
        import http.server

        # Multiple corpora: serve the results ROOT so every report is
        # reachable (results/<run_name>/index.html); a single corpus keeps
        # the report itself as the document root, as before.
        serve_dir = result.report_dir if len(results) == 1 else args.results_dir
        handler = functools.partial(
            http.server.SimpleHTTPRequestHandler, directory=serve_dir
        )
        with http.server.ThreadingHTTPServer(("127.0.0.1", args.serve), handler) as httpd:
            print(f"Serving the report at http://127.0.0.1:{httpd.server_address[1]}/ (Ctrl-C to stop)")
            try:
                httpd.serve_forever()
            except KeyboardInterrupt:
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
