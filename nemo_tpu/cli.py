"""nemo-tpu command-line interface.

CLI parity with the reference binary (main.go:68-78): `-faultInjOut` (required
path to the fault injector's output directory) and `-graphDBConn` (accepted
for compatibility; only meaningful to external-store backends).  Grows the
`--graph-backend={python,jax}` selector the north star prescribes
(SURVEY.md §0): `python` is the in-process oracle baseline, `jax` the
batched TPU backend.

Usage:
    python -m nemo_tpu.cli -faultInjOut <dir> [--graph-backend=jax]
"""

from __future__ import annotations

import argparse
import os
import sys

from nemo_tpu.analysis.pipeline import run_debug, run_debug_dirs
from nemo_tpu.obs import trace as obs_trace
from nemo_tpu.utils.jax_config import (
    PlatformUnavailableError,
    enable_compilation_cache,
    ensure_platform,
    pin_platform,
)


def make_backend(name: str):
    if name == "python":
        from nemo_tpu.backend.python_ref import PythonBackend

        return PythonBackend()
    if name == "jax":
        from nemo_tpu.backend.jax_backend import JaxBackend

        return JaxBackend()
    if name == "neo4j":
        from nemo_tpu.backend.neo4j_backend import Neo4jBackend

        return Neo4jBackend()
    if name == "service":
        from nemo_tpu.backend.service_backend import ServiceBackend

        return ServiceBackend()
    raise SystemExit(
        f"unknown graph backend: {name!r} (expected python, jax, neo4j, or service)"
    )


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "query":
        # `nemo-tpu query "<text>" -faultInjOut DIR` — the ad-hoc query
        # subcommand (nemo_tpu/query).  Dispatched before the main parser
        # because the query text is positional and the main CLI is
        # flag-only (Go flag-style reference parity).
        return _query_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="nemo-tpu", description="Provenance-graph debugging of distributed protocols."
    )
    # Single-dash long options for reference CLI parity (Go flag style).
    parser.add_argument(
        "-faultInjOut",
        "--fault-inj-out",
        dest="fault_inj_out",
        action="append",
        help="file system path to output directory of fault injector.  "
        "Repeatable: several corpus directories analyze in ONE run through "
        "the overlapped multi-corpus driver (corpus k+1's ingest and the "
        "figure pipeline ride under corpus k's analysis), one report per "
        "directory under --results-dir",
    )
    parser.add_argument(
        "-graphDBConn",
        "--graph-db-conn",
        dest="graph_db_conn",
        default="bolt://127.0.0.1:7687",
        help="connection URI for external graph-database backends (unused by "
        "the in-process backends)",
    )
    parser.add_argument(
        "--graph-backend",
        choices=("python", "jax", "neo4j", "service"),
        default="python",
        help="graph analytics engine: in-process Python oracle, batched "
        "JAX/TPU, a Neo4j server at -graphDBConn (the reference's backend), "
        "or the gRPC TPU sidecar at -graphDBConn (host:port; start it with "
        "python -m nemo_tpu.service.server)",
    )
    parser.add_argument(
        "--results-dir",
        default=os.path.join(os.getcwd(), "results"),
        help="root directory for generated reports (default ./results)",
    )
    parser.add_argument(
        "--timings", action="store_true", help="print per-phase wall-clock timings"
    )
    parser.add_argument(
        "--serve",
        metavar="PORT",
        type=int,
        default=0,
        help="after generating the report, serve it on http://127.0.0.1:PORT "
        "(browsers block fetch() on file:// URLs, so the report's "
        "debugging.json load needs an HTTP origin)",
    )
    parser.add_argument(
        "--profile",
        metavar="DIR",
        default=None,
        help="capture a jax.profiler trace of the analysis phases into DIR "
        "(view with TensorBoard/xprof)",
    )
    parser.add_argument(
        "--trace",
        metavar="FILE",
        default=None,
        help="write a Chrome-trace-event JSON of host spans (pipeline "
        "phases, kernel dispatches, render workers, RPC client+server) to "
        "FILE — open it at ui.perfetto.dev.  Equivalent env: NEMO_TRACE.  "
        "Near-zero overhead when off",
    )
    parser.add_argument(
        "--metrics-out",
        metavar="FILE",
        default=None,
        help="after the run, dump the obs metrics registry one-shot in "
        "Prometheus text format to FILE ('-' for stdout) — the same "
        "rendering the sidecar serves on --metrics-port",
    )
    parser.add_argument(
        "--figures",
        default="all",
        metavar="POLICY",
        help="figure materialization policy: 'all' (reference behavior), "
        "'failed' (failed runs + the good baseline run), 'sample:N' "
        "(failed + good + N sampled runs), or 'none'.  debugging.json "
        "always covers every run; at 10k+ run scale rendering every "
        "figure dominates wall clock",
    )
    parser.add_argument(
        "--render-workers",
        type=int,
        default=None,
        metavar="N",
        help="figure-render worker processes (default $NEMO_RENDER_WORKERS "
        "or cpu count; 1 renders inline).  Unique figures only: figures "
        "are deduplicated by render content and served from the "
        "persistent SVG cache before any worker runs",
    )
    parser.add_argument(
        "--svg-cache",
        default=None,
        metavar="DIR",
        help="persistent SVG cache directory (default $NEMO_SVG_CACHE or "
        "~/.cache/nemo_tpu/svg; 'off' disables).  Keyed by (render "
        "content hash, renderer version), so warm re-reports skip "
        "rendering entirely",
    )
    parser.add_argument(
        "--platform",
        default=None,
        metavar="NAME",
        help="jax platform: 'auto' (probe the device under a watchdog, fall "
        "back to CPU if unreachable — the environment's TPU tunnel HANGS "
        "device discovery during outages), 'cpu', 'tpu', or a concrete "
        "platform name (default: $NEMO_PLATFORM or auto)",
    )
    parser.add_argument(
        "--save-corpus",
        metavar="PATH",
        default=None,
        help="after ingestion, persist the packed-array corpus as a .npz "
        "bundle (graphs/corpus.py) so analysis can be re-run without "
        "re-parsing the Molly output",
    )
    parser.add_argument(
        "--corpus-cache",
        default=None,
        metavar="DIR|off",
        help="persistent memory-mapped corpus store root (default "
        "$NEMO_CORPUS_CACHE or ~/.cache/nemo_tpu/corpus; 'off' disables).  "
        "The packed ingest path parses each Molly directory ONCE and then "
        "mmap-loads the packed arrays in milliseconds; growing directories "
        "are appended to incrementally, and any mismatch (fingerprint, "
        "version, checksum) falls back loudly to the parse path",
    )
    parser.add_argument(
        "--result-cache",
        default=None,
        metavar="DIR|off",
        help="content-addressed analysis result cache root (default "
        "$NEMO_RESULT_CACHE or ~/.cache/nemo_tpu/results; 'off' disables).  "
        "Keyed by (corpus store segment fingerprints, figure policy, "
        "kernel/report ABI): a repeat request over an unchanged corpus "
        "restores the full report with zero kernel dispatches, and a "
        "grown corpus re-analyzes only its new runs, merging cached "
        "per-segment partials (analysis/delta.py).  Requires the corpus "
        "store (--corpus-cache) — without store fingerprints nothing "
        "content-addresses the corpus, so every request recomputes",
    )
    parser.add_argument(
        "--ingest",
        default="auto",
        choices=("auto", "native", "python"),
        help="ETL selection: 'native' parses+packs all provenance in the "
        "C++ engine (array backends only), 'python' builds the object "
        "tree, 'auto' (default) picks native when the backend supports "
        "packed ingest and the library builds",
    )
    parser.add_argument(
        "--injector",
        choices=("auto", "molly", "trace-json"),
        default=None,
        help="fault-injector front end (ingest/adapters.py): 'molly' "
        "(runs.json + per-run provenance files), 'trace-json' (one "
        "trace.json document, Jepsen-style histories), or 'auto' "
        "(default; sniffs the directory layout).  Equivalent env: "
        "NEMO_INJECTOR",
    )
    parser.add_argument(
        "--calibrate",
        action="store_true",
        help="run the platform microprobe calibration now (bounded by "
        "$NEMO_PROFILE_BUDGET_S, default 8s), persist the "
        "fingerprint-keyed profile under ~/.cache/nemo_tpu/platform, and "
        "print the resolved routing-constant table.  Recalibrates even "
        "over an existing profile.  Standalone (no -faultInjOut) exits "
        "after calibrating",
    )
    parser.add_argument(
        "--profile-mode",
        choices=("auto", "off", "force"),
        default=None,
        help="platform-profile policy (nemo_tpu/platform): 'auto' loads "
        "the measured profile and calibrates once per fingerprint, 'off' "
        "resolves every routing constant env/seeded (pre-profile "
        "behavior, bit-for-bit), 'force' recalibrates once per process.  "
        "Equivalent env: NEMO_PROFILE",
    )
    parser.add_argument(
        "--watch",
        action="store_true",
        help="live mode (ISSUE 15): tail the (single) -faultInjOut "
        "directory WHILE the fault injector runs — each batch of new "
        "runs is store-appended, delta-analyzed (O(new runs) with the "
        "corpus store + result cache on), and the report under "
        "--results-dir is atomically republished.  Combine with --serve "
        "to watch violations appear live in the browser; Ctrl-C stops",
    )
    parser.add_argument(
        "--watch-poll-s",
        type=float,
        default=None,
        metavar="S",
        help="watch poll interval (default $NEMO_WATCH_POLL_S or 0.5)",
    )
    parser.add_argument(
        "--watch-debounce-s",
        type=float,
        default=None,
        metavar="S",
        help="watch debounce: the sweep directory must hold still this "
        "long before a cycle analyzes (default $NEMO_WATCH_DEBOUNCE_S "
        "or 0.25)",
    )
    parser.add_argument(
        "--watch-max-updates",
        type=int,
        default=0,
        metavar="N",
        help="stop watching after N published updates (0 = until Ctrl-C)",
    )
    parser.add_argument(
        "--replay",
        metavar="SRC_DIR",
        default=None,
        help="deterministic live-sweep simulator: replay the FINISHED "
        "corpus at SRC_DIR into the watched -faultInjOut directory in "
        "--replay-generations monotonic prefixes, one every "
        "--replay-interval-s — the smoke/bench driver for --watch",
    )
    parser.add_argument(
        "--replay-generations", type=int, default=3, metavar="N",
        help="replay generation count (default 3)",
    )
    parser.add_argument(
        "--replay-interval-s", type=float, default=1.0, metavar="S",
        help="pause between replay generations (default 1.0)",
    )
    args = parser.parse_args(argv)

    dirs = args.fault_inj_out or []
    if not dirs and not args.calibrate:
        parser.error("-faultInjOut is required (unless --calibrate runs standalone)")
    if args.watch and len(dirs) != 1:
        parser.error("--watch takes exactly one -faultInjOut directory")
    if args.replay and not args.watch:
        parser.error("--replay only makes sense with --watch")
    for d in dirs:
        if not os.path.isdir(d):
            if args.watch:
                # A watcher may legitimately start BEFORE the model
                # checker's first flush (or before the replay driver's
                # first generation) creates the sweep directory.
                os.makedirs(d, exist_ok=True)
            else:
                parser.error(f"fault injector output directory not found: {d}")
    if len(dirs) > 1 and args.save_corpus:
        parser.error(
            "--save-corpus is incompatible with multiple -faultInjOut "
            "directories (every corpus would overwrite the same bundle); "
            "run per directory with distinct paths"
        )

    # Tracing: the flag wins, NEMO_TRACE is the env equivalent.  The trace
    # is written explicitly before the final prints below (so the path is
    # announced), with an atexit backstop for crash paths.  The env is NOT
    # mutated: main() may run many times in one process (tests).
    if args.trace:
        import atexit

        obs_trace.start_trace(args.trace)
        atexit.register(obs_trace.finish)
    else:
        obs_trace.configure_from_env()

    if args.graph_backend == "jax":
        # The only backend that touches the accelerator in-process; resolve
        # the platform under a watchdog so a tunnel outage degrades to CPU
        # instead of hanging (the reference CLI always terminates,
        # main.go:65-292 — every error is log.Fatalf).
        try:
            platform = ensure_platform(args.platform)
        except PlatformUnavailableError as e:
            # Explicit --platform=tpu with no reachable device: terminate
            # nonzero (log.Fatalf semantics) rather than silently degrading.
            print(f"fatal: {e}", file=sys.stderr)
            return 2
        print(f"jax platform: {platform}", file=sys.stderr)
    else:
        # python/neo4j run no device code; the service backend's device
        # lives in the sidecar process.  Pin CPU unless the user explicitly
        # asked otherwise, so stray jax imports can't block on tunnel health.
        pin_platform(args.platform if args.platform not in (None, "", "auto") else "cpu")
    enable_compilation_cache()
    # The render knobs travel as env so the resolution is identical across
    # the CLI, the bench, and run_debug_dirs (report/render.py reads them).
    if args.render_workers is not None:
        os.environ["NEMO_RENDER_WORKERS"] = str(args.render_workers)
    if args.svg_cache is not None:
        os.environ["NEMO_SVG_CACHE"] = args.svg_cache
    if args.corpus_cache is not None:
        os.environ["NEMO_CORPUS_CACHE"] = args.corpus_cache
    if args.result_cache is not None:
        os.environ["NEMO_RESULT_CACHE"] = args.result_cache
    if args.injector is not None:
        os.environ["NEMO_INJECTOR"] = args.injector
    if args.profile_mode is not None:
        os.environ["NEMO_PROFILE"] = args.profile_mode
    if args.calibrate:
        code = _calibrate_main()
        if not dirs:
            return code
    if args.watch:
        return _watch_main(args, dirs[0])

    # The tracer is finished in the finally: a pipeline failure must still
    # write the partial trace (a trace of a failed run is exactly when you
    # want one) AND disable the global tracer — main() may run again in
    # this process, and a stale enabled tracer would silently swallow the
    # next run's spans into the old file.
    try:
        if len(dirs) == 1:
            result = run_debug(
                dirs[0],
                args.results_dir,
                make_backend(args.graph_backend),
                conn=args.graph_db_conn,
                save_corpus_path=args.save_corpus,
                profile_dir=args.profile,
                figures=args.figures,
                ingest=args.ingest,
            )
            results = [result]
        else:
            results = run_debug_dirs(
                dirs,
                args.results_dir,
                lambda: make_backend(args.graph_backend),
                conn=args.graph_db_conn,
                profile_dir=args.profile,
                figures=args.figures,
                ingest=args.ingest,
            )
            result = results[-1]
    except BaseException:
        trace_path = obs_trace.finish()
        if trace_path:
            print(
                f"obs trace (partial, run failed) written to {trace_path}",
                file=sys.stderr,
            )
        raise

    if args.timings:
        for res in results:
            if len(results) > 1:
                print(f"--- {res.molly.run_name}")
            for phase, secs in res.timings.items():
                print(f"{phase:>22s}  {secs * 1e3:9.1f} ms")
        fs = result.figure_stats
        if fs and fs.get("figures"):
            print(
                f"figures: {fs['figures']} rendered as {fs['unique_figures']} "
                f"unique (dedup {fs['dedup_ratio']}x), "
                f"{fs['figure_cache_hits']} cache hits, "
                f"{fs['render_workers']} render workers"
            )

    trace_path = obs_trace.finish()
    if trace_path:
        print(f"obs trace written to {trace_path} (open at ui.perfetto.dev)")

    if args.metrics_out:
        from nemo_tpu.obs import promexp

        text = promexp.render_prometheus()
        if args.metrics_out == "-":
            sys.stdout.write(text)
        else:
            with open(args.metrics_out, "w", encoding="utf-8") as fh:
                fh.write(text)
            print(f"metrics written to {args.metrics_out} (Prometheus text format)")

    for res in results:
        print(f"All done! Find the debug report here: {os.path.join(res.report_dir, 'index.html')}")

    if args.serve:
        import http.server

        # Multiple corpora: serve the results ROOT so every report is
        # reachable (results/<run_name>/index.html); a single corpus keeps
        # the report itself as the document root, as before.  The handler
        # adds POST /query over the in-memory corpora for the report's
        # query box.
        serve_dir = result.report_dir if len(results) == 1 else args.results_dir
        handler = _query_http_handler(serve_dir, _batch_molly_resolver(results))
        with http.server.ThreadingHTTPServer(("127.0.0.1", args.serve), handler) as httpd:
            print(f"Serving the report at http://127.0.0.1:{httpd.server_address[1]}/ (Ctrl-C to stop)")
            try:
                httpd.serve_forever()
            except KeyboardInterrupt:
                pass
    return 0


def _query_main(argv: list[str]) -> int:
    """`nemo-tpu query`: compile one declarative query onto the batched
    kernels and print the JSON result document (README "Ad-hoc queries").
    Exit 0 on success, 2 on a query error (parse/validation/unknown name —
    always loud, never an empty result)."""
    import json

    parser = argparse.ArgumentParser(
        prog="nemo-tpu query",
        description="Run one ad-hoc provenance query over a corpus directory.",
    )
    parser.add_argument(
        "query",
        help='query text, e.g. \'from pre match goal[holds=true] -> @rule '
        "tables' (language reference: README \"Ad-hoc queries\")",
    )
    parser.add_argument(
        "-faultInjOut",
        "--fault-inj-out",
        dest="fault_inj_out",
        required=False,
        help="fault injector output directory to query",
    )
    parser.add_argument(
        "--injector",
        default=None,
        help="fault-injector adapter for ingest (default: sniff; env NEMO_INJECTOR)",
    )
    parser.add_argument("--corpus-cache", metavar="DIR", default=None)
    parser.add_argument("--result-cache", metavar="DIR", default=None)
    parser.add_argument(
        "--platform",
        default=None,
        metavar="NAME",
        help="jax platform (auto/cpu/tpu; default $NEMO_PLATFORM or auto)",
    )
    parser.add_argument(
        "--explain",
        action="store_true",
        help="print the lowered kernel plan (one line per primitive) and exit "
        "without executing",
    )
    parser.add_argument(
        "--serial",
        action="store_true",
        help="drain query jobs serially instead of through the heterogeneous "
        "scheduler (debugging)",
    )
    args = parser.parse_args(argv)

    from nemo_tpu.query import QueryError, parse_query, plan_query

    try:
        q = parse_query(args.query)
    except QueryError as ex:
        print(f"query error: {ex}", file=sys.stderr)
        return 2
    if args.explain:
        for line in plan_query(q).describe():
            print(line)
        return 0

    if not args.fault_inj_out:
        parser.error("-faultInjOut is required (unless --explain)")
    if not os.path.isdir(args.fault_inj_out):
        parser.error(f"fault injector output directory not found: {args.fault_inj_out}")
    if args.corpus_cache is not None:
        os.environ["NEMO_CORPUS_CACHE"] = args.corpus_cache
    if args.result_cache is not None:
        os.environ["NEMO_RESULT_CACHE"] = args.result_cache
    if args.injector is not None:
        os.environ["NEMO_INJECTOR"] = args.injector
    try:
        ensure_platform(args.platform)
    except PlatformUnavailableError as e:
        print(f"fatal: {e}", file=sys.stderr)
        return 2
    enable_compilation_cache()

    from nemo_tpu.analysis.pipeline import _ingest
    from nemo_tpu.query.engine import execute_query
    from nemo_tpu.store import resolve_store

    molly = _ingest(args.fault_inj_out, use_packed=True, store=resolve_store())
    try:
        doc = execute_query(q, molly, serial=args.serial)
    except QueryError as ex:
        print(f"query error: {ex}", file=sys.stderr)
        return 2
    json.dump(doc, sys.stdout, indent=2, sort_keys=True)
    print()
    return 0


def _query_http_handler(serve_dir: str, resolve_molly):
    """SimpleHTTPRequestHandler subclass serving ``serve_dir`` statically
    PLUS a ``POST /query`` endpoint for the report front end's query box
    (report/assets/app.js).  ``resolve_molly(request_dict)`` returns the
    corpus to query — a closure over the in-memory result (batch mode) or
    a store-warm re-ingest (watch mode).  Query errors come back as JSON
    ``{"error": ...}`` with status 400, so the box can render them inline."""
    import functools
    import http.server
    import json

    class Handler(http.server.SimpleHTTPRequestHandler):
        def do_POST(self):  # noqa: N802 - http.server API
            if self.path.rstrip("/") != "/query":
                self.send_error(404, "unknown POST endpoint (expected /query)")
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(n).decode("utf-8") or "{}")
                if not isinstance(req, dict):
                    raise ValueError("request body must be a JSON object")
                from nemo_tpu.query import run_query_text

                doc = run_query_text(str(req.get("query", "")), resolve_molly(req))
                body, status = json.dumps(doc).encode("utf-8"), 200
            except Exception as ex:  # loud to the query box, not a 500 page
                body = json.dumps(
                    {"error": f"{type(ex).__name__}: {ex}"}
                ).encode("utf-8")
                status = 400
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return functools.partial(Handler, directory=serve_dir)


def _batch_molly_resolver(results):
    """Query-box corpus resolution for batch ``--serve``: one corpus binds
    directly; several (results-root serving) need the report name the
    front end sends (its first path segment)."""
    by_name = {res.molly.run_name: res.molly for res in results}

    def resolve(req: dict):
        if len(by_name) == 1:
            return next(iter(by_name.values()))
        name = str(req.get("report", ""))
        if name not in by_name:
            from nemo_tpu.query import QueryError

            raise QueryError(
                f"query box needs a report name to pick the corpus; got "
                f"{name!r} (one of: {', '.join(sorted(by_name))})"
            )
        return by_name[name]

    return resolve


def _watch_molly_resolver(sweep_dir: str, injector_arg):
    """Query-box corpus resolution for watch mode: re-ingest through the
    corpus store (warm hit mmaps in milliseconds), memoized on the
    adapter's poll token so queries between sweep generations reuse one
    MollyOutput and only a grown sweep re-ingests."""
    memo: dict = {}

    def resolve(req: dict):
        from nemo_tpu.analysis.pipeline import _ingest
        from nemo_tpu.ingest import adapters
        from nemo_tpu.store import resolve_store

        injector = adapters.resolve_injector(sweep_dir, injector_arg)
        token = injector.poll_token(sweep_dir)
        if memo.get("token") != token:
            memo["molly"] = _ingest(sweep_dir, use_packed=True, store=resolve_store())
            memo["token"] = token
        return memo["molly"]

    return resolve


def _calibrate_main() -> int:
    """--calibrate: force one bounded microprobe calibration for this
    platform fingerprint and print the resolved constant table (env >
    measured > seeded per row, the same precedence every consumer uses)."""
    from nemo_tpu.platform import profile as pp

    if pp.profile_mode() == "off":
        print(
            "platform profile disabled (NEMO_PROFILE=off); nothing to calibrate",
            file=sys.stderr,
        )
        return 2
    prof = pp.ensure_calibrated(force=True)
    if prof is None:
        print("calibration failed; constants stay seeded (see log)", file=sys.stderr)
        return 1
    fp = prof.fingerprint
    print(
        f"platform profile {prof.key} ({fp['platform']}/{fp['device_kind']} "
        f"x{fp['device_count']}, jax {fp['jax_version']}) calibrated in "
        f"{prof.calibration_wall_s:.2f}s -> {pp.profile_path(prof.key)}"
    )
    for row in pp.constant_sources():
        note = ""
        if row["source"] == "env" and row["measured"] is not None:
            note = f"  (measured {row['measured']:.6g})"
        print(f"  {row['name']:>24} = {row['value']} [{row['source']}]{note}")
    return 0


def _watch_main(args, sweep_dir: str) -> int:
    """The `--watch` live loop (ISSUE 15): a Watcher tails the sweep
    directory and republishes the report on every batch of new runs; with
    --serve the report HTTP server runs CONCURRENTLY so the browser shows
    invariant violations and ranked-repair shifts live mid-sweep.  Exits
    on Ctrl-C or after --watch-max-updates updates."""
    import threading

    from nemo_tpu.obs import trace as obs_trace
    from nemo_tpu.watch import WatchConfig, Watcher, start_replay

    cfg_kw: dict = {}
    if args.watch_poll_s is not None:
        cfg_kw["poll_s"] = args.watch_poll_s
    if args.watch_debounce_s is not None:
        cfg_kw["debounce_s"] = args.watch_debounce_s
    cfg = WatchConfig(
        max_updates=args.watch_max_updates,
        figures=args.figures,
        injector=args.injector,
        **cfg_kw,
    )
    watcher = Watcher(
        sweep_dir,
        args.results_dir,
        lambda: make_backend(args.graph_backend),
        cfg,
        conn=args.graph_db_conn,
    )
    q = watcher.subscribe()

    def printer() -> None:
        while True:
            ev = q.get()
            if ev.get("event") == "report_update":
                print(
                    f"watch update {ev['update']}: {ev['runs_total']} runs "
                    f"(+{ev['new_runs']} new, {ev['runs_mapped']} mapped, "
                    f"{ev['segments_cached']} segments cached), "
                    f"{ev['changed_total']} sections changed, "
                    f"{ev['update_latency_s']:.2f}s"
                )
            elif ev.get("event") == "watch_error":
                print(f"watch cycle failed: {ev['detail']}", file=sys.stderr)

    threading.Thread(target=printer, daemon=True, name="nemo-watch-print").start()

    httpd = None
    if args.serve:
        import http.server

        # POST /query re-ingests through the corpus store (memoized on the
        # adapter poll token), so the query box stays live mid-sweep.
        handler = _query_http_handler(
            args.results_dir, _watch_molly_resolver(sweep_dir, args.injector)
        )
        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", args.serve), handler)
        threading.Thread(
            target=httpd.serve_forever, daemon=True, name="nemo-watch-http"
        ).start()
        print(
            f"Serving live reports at "
            f"http://127.0.0.1:{httpd.server_address[1]}/ (Ctrl-C to stop)"
        )

    replay_stop = None
    if args.replay:
        _, replay_stop = start_replay(
            args.replay,
            sweep_dir,
            generations=args.replay_generations,
            interval_s=args.replay_interval_s,
            injector=args.injector,
        )
    try:
        watcher.run()
    except KeyboardInterrupt:
        watcher.stop()
    finally:
        if replay_stop is not None:
            replay_stop.set()
        if httpd is not None:
            httpd.shutdown()
        trace_path = obs_trace.finish()
        if trace_path:
            print(f"obs trace written to {trace_path} (open at ui.perfetto.dev)")
    if watcher.report_dir:
        print(
            f"watch finished after {watcher.updates} updates; live report: "
            f"{os.path.join(watcher.report_dir, 'index.html')}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
