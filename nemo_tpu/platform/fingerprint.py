"""Platform fingerprint — the identity a measured profile is keyed by.

A profile is only trustworthy on the hardware/software stack it was
measured on, so every constant the calibrator persists is keyed by the
5-tuple the routing economics actually depend on: the jax platform
(cpu/tpu/gpu), the device kind string, the device count, the jax version
(XLA codegen changes move walls), and the analysis kernel ABI (a kernel
rewrite invalidates measured dispatch costs as surely as new silicon).
Any change produces a different key, so a stale profile is never loaded —
it is simply never found, and the first run on the new stack recalibrates
loudly (platform/profile.py:ensure_calibrated).
"""

from __future__ import annotations

import hashlib
import json


def platform_fingerprint() -> dict:
    """The identity dict (JSON-able, stable key order via sorted dump).
    Imports jax lazily: fingerprinting must be callable from stdlib-only
    surfaces (obs/flight.py embeds it) without forcing a jax init there —
    those callers only ever see it through an already-imported profile
    module."""
    import jax

    from nemo_tpu.analysis.delta import ANALYSIS_ABI_VERSION

    devices = jax.devices()
    return {
        "platform": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "none",
        "device_count": len(devices),
        "jax_version": jax.__version__,
        "analysis_abi": int(ANALYSIS_ABI_VERSION),
    }


def fingerprint_key(fp: dict) -> str:
    """Short stable content key of a fingerprint dict — the profile file
    name component (profile-<key>.json) and the cross-check stamp inside
    the file."""
    blob = json.dumps(fp, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]
