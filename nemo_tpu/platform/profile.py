"""The persistent platform profile: measured routing constants + precedence.

One JSON document per platform fingerprint (platform/fingerprint.py),
stored under the shared cache root (``~/.cache/nemo_tpu/platform/
profile-<key>.json``, honoring ``XDG_CACHE_HOME``; ``NEMO_PROFILE_DIR``
relocates it).  It holds the calibrator's fitted constants (platform/
calibrate.py), the probe measurements they were fitted from, and the
scheduler's per-(verb, V, E) EWMA walls folded back at clean shutdown —
the cross-session warm start.

Every consumer resolves each constant with ONE precedence rule, recorded
per constant so telemetry can show where a number came from:

    env var set        -> ``env``      (the operator always wins; the
                                        consumer's own parser still applies,
                                        so legacy env semantics are exact)
    measured profile   -> ``measured`` (this module's ``profile_value``)
    neither            -> ``seeded``   (the hand-tuned PR-3/4 defaults)

``NEMO_PROFILE`` gates the whole subsystem: ``auto`` (default) loads the
fingerprint's profile and calibrates once when none exists, ``off``
disables both load and calibration (every constant resolves env/seeded —
bit-for-bit today's behavior), ``force`` recalibrates even over an
existing profile.  Invalidation semantics: a fingerprint change simply
misses the keyed file and recalibrates loudly; a CORRUPT profile file
falls back to seeded defaults with ``profile.stale`` counted (corruption
is a storage fault, not a reason to burn a calibration the operator
didn't ask for).
"""

from __future__ import annotations

import atexit
import json
import os
import tempfile
import threading
import time

from nemo_tpu import obs
from nemo_tpu.obs import log as _obs_log
from nemo_tpu.utils.env import env_choice, env_float

from .fingerprint import fingerprint_key, platform_fingerprint

_log = _obs_log.get_logger("nemo.platform")

#: Bump when the profile document schema changes incompatibly; a mismatch
#: reads as corrupt (seeded fallback + profile.stale), never as measured.
PROFILE_ABI_VERSION = 1

#: constant name -> (env var, seeded default, constant-group).  The seeded
#: defaults are the documented hand-tuned values each consumer carries —
#: kept HERE only for the telemetry table; consumers keep their own
#: defaults so NEMO_PROFILE=off touches nothing.  sched_device_fixed's
#: seed is derived (budget x unit spread, parallel/sched.py:default_models),
#: hence None.
CONSTANTS: dict[str, tuple[str, float | None, str]] = {
    "analysis_host_work": ("NEMO_ANALYSIS_HOST_WORK", 100000, "routing"),
    "synth_host_work": ("NEMO_SYNTH_HOST_WORK", 100000, "routing"),
    "diff_host_work": ("NEMO_DIFF_HOST_WORK", 2000000, "routing"),
    "sparse_device_mem_mb": ("NEMO_SPARSE_DEVICE_MEM_MB", 256.0, "routing"),
    "sparse_device_density": ("NEMO_SPARSE_DEVICE_DENSITY", 1.0 / 256.0, "routing"),
    "sched_host_unit": ("NEMO_SCHED_HOST_UNIT", 1e-6, "sched"),
    "sched_device_unit": ("NEMO_SCHED_DEVICE_UNIT", 5e-8, "sched"),
    "sched_sparse_device_unit": ("NEMO_SCHED_SPARSE_DEVICE_UNIT", 2.5e-7, "sched"),
    "sched_device_fixed": ("NEMO_SCHED_DEVICE_FIXED", None, "sched"),
    "sched_flops_per_s": ("NEMO_SCHED_FLOPS_PER_S", 5e9, "pricing"),
}

#: Encoded profile.source.<group> gauge values (federation-friendly).
_SOURCE_CODE = {"seeded": 0, "measured": 1, "env": 2}


def profile_mode() -> str:
    """``NEMO_PROFILE``: auto | off | force.  Loud policy — this knob pins
    which constants route the whole corpus."""
    return env_choice("NEMO_PROFILE", "auto", ("auto", "off", "force"))


def profile_budget_s() -> float:
    """``NEMO_PROFILE_BUDGET_S`` (default 8): wall-clock budget for one
    calibration.  Probes check the deadline between steps and early-stop
    keeping partial fits (unfitted constants stay seeded)."""
    return env_float("NEMO_PROFILE_BUDGET_S", 8.0, minimum=0.5)


def profile_dir() -> str:
    d = os.environ.get("NEMO_PROFILE_DIR")
    if d:
        return d
    cache = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(cache, "nemo_tpu", "platform")


def profile_path(key: str) -> str:
    return os.path.join(profile_dir(), f"profile-{key}.json")


class PlatformProfile:
    """In-memory view of one profile document (the JSON schema, 1:1)."""

    def __init__(
        self,
        fingerprint: dict,
        constants: dict | None = None,
        probes: dict | None = None,
        ewma: dict | None = None,
        calibration_wall_s: float = 0.0,
        created: float | None = None,
        updated: float | None = None,
    ) -> None:
        self.fingerprint = dict(fingerprint)
        self.key = fingerprint_key(self.fingerprint)
        #: name -> {"value": float, "measured": bool} — measured=False
        #: entries are honest "still seeded" records (e.g. the density
        #: crossover on a platform where no sparse probe ran).
        self.constants = dict(constants or {})
        #: Raw probe measurements the fit came from (audit trail).
        self.probes = dict(probes or {})
        #: lane -> {"verb|v|e": EWMA seconds-per-row} — the scheduler's
        #: cross-session memory (fold_back_session / warm_start).
        self.ewma = {lane: dict(d) for lane, d in (ewma or {}).items()}
        self.calibration_wall_s = float(calibration_wall_s)
        self.created = float(created if created is not None else time.time())
        self.updated = float(updated if updated is not None else self.created)

    def measured_value(self, name: str) -> float | None:
        rec = self.constants.get(name)
        if rec and rec.get("measured") and rec.get("value") is not None:
            return float(rec["value"])
        return None

    def set_constant(self, name: str, value: float, measured: bool = True) -> None:
        self.constants[name] = {"value": float(value), "measured": bool(measured)}

    def age_s(self) -> float:
        return max(time.time() - self.updated, 0.0)

    def to_doc(self) -> dict:
        return {
            "abi": PROFILE_ABI_VERSION,
            "fingerprint": self.fingerprint,
            "key": self.key,
            "constants": self.constants,
            "probes": self.probes,
            "ewma": self.ewma,
            "calibration_wall_s": self.calibration_wall_s,
            "created": self.created,
            "updated": self.updated,
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "PlatformProfile":
        if doc.get("abi") != PROFILE_ABI_VERSION:
            raise ValueError(f"profile ABI {doc.get('abi')!r} != {PROFILE_ABI_VERSION}")
        prof = cls(
            doc["fingerprint"],
            constants=doc.get("constants"),
            probes=doc.get("probes"),
            ewma=doc.get("ewma"),
            calibration_wall_s=doc.get("calibration_wall_s", 0.0),
            created=doc.get("created"),
            updated=doc.get("updated"),
        )
        if doc.get("key") != prof.key:
            raise ValueError(
                f"profile key {doc.get('key')!r} does not match its own "
                f"fingerprint ({prof.key})"
            )
        return prof

    def save(self) -> str:
        """Atomic write (tmp + rename) — a crashed process never leaves a
        half-written profile for the next boot to read as corrupt."""
        path = profile_path(self.key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path), prefix=".profile-", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(self.to_doc(), f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path


# ---------------------------------------------------------------------------
# process-global active profile
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
#: "unloaded" sentinel vs "loaded but None" (mode off / no file / corrupt).
_UNSET = object()
_ACTIVE: object = _UNSET
#: Whether THIS process already ran a calibration (force recalibrates once
#: per process, not once per corpus).
_CALIBRATED = False
#: The last load found a PRESENT but unreadable/mismatched file — the
#: corruption latch ensure_calibrated consults so a storage fault falls
#: back to seeded defaults instead of burning a surprise recalibration
#: (``force`` still recalibrates over it, by explicit request).
_CORRUPT = False
_ATEXIT_REGISTERED = False


def reset_active_profile() -> None:
    """Forget the cached profile + calibration/corruption latches (tests)."""
    global _ACTIVE, _CALIBRATED, _CORRUPT
    with _LOCK:
        _ACTIVE = _UNSET
        _CALIBRATED = False
        _CORRUPT = False


def _load_for_fingerprint() -> PlatformProfile | None:
    """Load the current fingerprint's profile file, or None when missing.
    A present-but-unreadable file is the CORRUPTION case: seeded fallback,
    ``profile.stale`` counted, warning logged — never a surprise
    recalibration over a storage fault (the ``_CORRUPT`` latch)."""
    global _CORRUPT
    fp = platform_fingerprint()
    path = profile_path(fingerprint_key(fp))
    if not os.path.isfile(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        prof = PlatformProfile.from_doc(doc)
        if prof.fingerprint != fp:
            raise ValueError("embedded fingerprint does not match this platform")
        return prof
    except (OSError, ValueError, KeyError, TypeError) as ex:
        _CORRUPT = True
        obs.metrics.inc("profile.stale")
        _log.warning(
            "profile.stale", path=path, error=str(ex), action="seeded defaults"
        )
        return None


def active_profile() -> PlatformProfile | None:
    """The loaded profile for this process, or None (mode off, no file
    yet, or a corrupt file).  Loads at most once per process; NEVER
    calibrates — that is ensure_calibrated's job, called from the backend
    setup path where probe dispatches are legal."""
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is _UNSET:
            if profile_mode() == "off":
                _ACTIVE = None
            else:
                prof = _load_for_fingerprint()
                if prof is not None:
                    obs.metrics.inc("profile.loaded")
                    _register_fold_back_locked()
                _ACTIVE = prof
            _record_metrics_locked()
        return _ACTIVE  # type: ignore[return-value]


def ensure_calibrated(force: bool = False) -> PlatformProfile | None:
    """The calibration trigger (backend/jax_backend.py:init_graph_db, the
    CLI --calibrate verb, serve boot): under ``auto`` with no profile on
    disk run ONE bounded microprobe suite and persist it; ``force`` (the
    env mode or the keyword — the CLI verb's explicit request)
    recalibrates once per process even over an existing file; ``off``
    does nothing.  Never raises — a failed calibration logs, counts
    ``profile.error``, and leaves every constant seeded."""
    global _ACTIVE, _CALIBRATED
    mode = profile_mode()
    if mode == "off":
        return active_profile()
    force = force or mode == "force"
    prof = active_profile()
    with _LOCK:
        # Missing profile -> calibrate; CORRUPT file -> seeded fallback
        # (storage faults never burn a calibration) unless forced.
        want = (prof is None and not _CORRUPT) or (force and not _CALIBRATED)
        if not want:
            return prof
        _CALIBRATED = True
    fp = platform_fingerprint()
    _log.warning(
        "profile.calibrating",
        fingerprint=fp,
        reason="forced" if force else "no profile for this fingerprint",
        budget_s=profile_budget_s(),
    )
    try:
        from .calibrate import run_calibration

        new = run_calibration()
        new.save()
        obs.metrics.inc("profile.calibrated")
    except Exception as ex:
        obs.metrics.inc("profile.error")
        _log.warning("profile.calibration_failed", error=str(ex), action="seeded defaults")
        return prof
    with _LOCK:
        _ACTIVE = new
        _register_fold_back_locked()
        _record_metrics_locked()
    return new


def profile_value(name: str) -> float | None:
    """The MEASURED value of one constant, or None when the profile is
    off/absent or the constant stayed seeded.  Consumers call this as
    their default when the env var is unset — env precedence lives in the
    consumer, so legacy env parsing is untouched."""
    prof = active_profile()
    return None if prof is None else prof.measured_value(name)


def _constant_rows(prof: PlatformProfile | None) -> list[dict]:
    rows = []
    for name, (env_var, seeded, group) in CONSTANTS.items():
        measured = None if prof is None else prof.measured_value(name)
        env_raw = os.environ.get(env_var)
        if env_raw is not None:
            source, value = "env", env_raw
        elif measured is not None:
            source, value = "measured", measured
        else:
            source, value = "seeded", seeded
        rows.append(
            {
                "name": name,
                "env": env_var,
                "group": group,
                "source": source,
                "value": value,
                "measured": measured,
            }
        )
    return rows


def constant_sources() -> list[dict]:
    """Per-constant resolution table (telemetry + flight recorder): the
    resolved value, its source (env > measured > seeded), and the measured
    record even when an env override wins — overriding must not suppress
    the measurement."""
    return _constant_rows(active_profile())


def _record_metrics_locked() -> None:
    """profile.source.<group> / profile.age_s / profile.calibration_s
    gauges — gauges so the fleet federation surface (obs/federation.py)
    rolls them up per replica for free."""
    try:
        prof = _ACTIVE if isinstance(_ACTIVE, PlatformProfile) else None
        groups: dict[str, int] = {}
        for row in _constant_rows(prof):
            code = _SOURCE_CODE[row["source"]]
            groups[row["group"]] = max(groups.get(row["group"], 0), code)
        for group, code in groups.items():
            obs.metrics.gauge(f"profile.source.{group}", code)
        if prof is not None:
            obs.metrics.gauge("profile.age_s", prof.age_s())
            obs.metrics.gauge("profile.calibration_s", prof.calibration_wall_s)
    except Exception:  # lint: allow-silent-except — metrics are observability, never control flow (docstring)
        pass


def telemetry_section() -> dict:
    """The ``platform_profile`` section of telemetry.json (rendered as a
    report table by report/assets/app.js) — also embedded verbatim in
    flight-recorder bundles and BENCH captures."""
    prof = active_profile()
    sect: dict = {"mode": profile_mode(), "constants": constant_sources()}
    if prof is not None:
        sect.update(
            fingerprint=prof.fingerprint,
            key=prof.key,
            calibration_wall_s=round(prof.calibration_wall_s, 4),
            age_s=round(prof.age_s(), 1),
            ewma_classes={lane: len(d) for lane, d in prof.ewma.items()},
        )
    return sect


# ---------------------------------------------------------------------------
# cross-session scheduler memory (EWMA fold-back + warm start)
# ---------------------------------------------------------------------------


def _ewma_key(verb: str, v: int, e: int) -> str:
    return f"{verb}|{v}|{e}"


def _ewma_unkey(key: str) -> tuple[str, int, int] | None:
    parts = key.split("|")
    if len(parts) != 3:
        return None
    try:
        return parts[0], int(parts[1]), int(parts[2])
    except ValueError:
        return None


def warm_start(models: dict) -> None:
    """Seed freshly-built session LaneModels' per-(verb,V,E) EWMA tables
    from the profile's folded-back walls (parallel/sched.py:session_models
    calls this once per process) — a new session predicts from the LAST
    session's measurements instead of the static seed line."""
    prof = active_profile()
    if prof is None:
        return
    loaded = 0
    for lane, model in models.items():
        for key, per_row in prof.ewma.get(lane, {}).items():
            parsed = _ewma_unkey(key)
            if parsed is not None and per_row > 0:
                model.per_row[parsed] = float(per_row)
                loaded += 1
    if loaded:
        obs.metrics.inc("profile.ewma_warm_start", loaded)
    _register_fold_back()


def _register_fold_back() -> None:
    with _LOCK:
        _register_fold_back_locked()


def _register_fold_back_locked() -> None:
    global _ATEXIT_REGISTERED
    if not _ATEXIT_REGISTERED:
        atexit.register(fold_back_session)
        _ATEXIT_REGISTERED = True


def fold_back_session() -> None:
    """At clean shutdown, merge this session's measured per-(verb,V,E)
    EWMA walls (parallel/sched._SESSION_MODELS) back into the profile and
    rewrite it atomically — staleness-stamped (``updated``) and
    fingerprint-keyed, so the next session on the SAME platform warm
    starts and a different platform never sees these walls.  Never raises
    (registered atexit)."""
    try:
        with _LOCK:
            prof = _ACTIVE if isinstance(_ACTIVE, PlatformProfile) else None
        if prof is None:
            return
        import sys

        sch = sys.modules.get("nemo_tpu.parallel.sched")
        if sch is None:
            return
        models = getattr(sch, "_SESSION_MODELS", None)
        if not models:
            return
        folded = 0
        for lane, model in models.items():
            table = prof.ewma.setdefault(lane, {})
            for (verb, v, e), per_row in getattr(model, "per_row", {}).items():
                key = _ewma_key(verb, v, e)
                old = table.get(key)
                table[key] = (
                    float(per_row) if old is None else 0.5 * float(old) + 0.5 * float(per_row)
                )
                folded += 1
        if not folded:
            return
        prof.updated = time.time()
        prof.save()
        obs.metrics.inc("profile.fold_back", folded)
    except Exception:  # lint: allow-silent-except — shutdown persistence is best-effort; a failed fold-back must not mask the process's real exit (docstring)
        pass
