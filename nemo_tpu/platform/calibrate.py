"""The bounded microprobe suite: measure the platform, fit the constants.

``run_calibration`` dispatches a few-second probe set through the SAME
code paths the deployment uses — the fused analysis verb via
``LocalExecutor.run`` at two batch widths of the exact stress signature
the pipeline compiles (utils/prewarm.py:stress_signature, so the probe
compiles land in the shared jit + persistent caches and a serve boot's
prewarm reuses them), the sparse host engine via
``ops/sparse_host.sparse_analysis_step`` on the same packed arrays, a
host->device transfer-bandwidth sample, and the compile wall of the cold
fused dispatch — then fits the routing constants:

  * ``sched_host_unit``       host wall / work (work = B x (V + E), the
                              route planner's unit)
  * ``sched_device_unit``     slope of the two warm device walls over work
  * ``sched_device_fixed``    their intercept (dispatch RTT + launch)
  * ``analysis_host_work``    fixed / (host_unit - device_unit) — where
                              the two lane lines cross, the PR-3 break-even
                              re-derived from measurement
  * ``synth_host_work``       same crossover (the seeded 1:1 economics)
  * ``diff_host_work``        20x the analysis crossover (the seeded
                              2M:100k ratio, anchored to the measured value)
  * ``sched_sparse_device_unit``  5x the measured device unit (the seeded
                              ratio; no sparse-device probe dispatches)
  * ``sched_flops_per_s``     the cost table's FLOPs estimate over the
                              warm wall (measured only when the dispatch
                              was costed)
  * ``sparse_device_mem_mb``  25% of the PJRT per-device bytes_limit on
                              real accelerators; stays SEEDED on cpu
                              (host "device memory" is just RAM)
  * ``sparse_device_density`` stays seeded everywhere (no giant-V probe
                              fits in the budget) — recorded honestly as
                              measured=False

Every probe runs under an obs span (``profile:probe.<name>``) and checks
the wall-clock deadline (``NEMO_PROFILE_BUDGET_S``) between steps —
running out of budget keeps the partial fit, and any probe failure raises
out to ``ensure_calibrated``'s seeded fallback.
"""

from __future__ import annotations

import time

import numpy as np

from nemo_tpu import obs
from nemo_tpu.obs import log as _obs_log

from .fingerprint import platform_fingerprint
from .profile import PlatformProfile, profile_budget_s

_log = _obs_log.get_logger("nemo.platform")

#: Probe-corpus runs and the two fused batch widths: big enough to expose
#: the per-row slope, small enough that both compiles + warm reps fit the
#: default budget on a 1-core CPU container.
_PROBE_RUNS = 8
_PROBE_WIDTHS = (8, 32)
_WARM_REPS = 3
_TRANSFER_MB = 4


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


def _probe_transfer(prof: PlatformProfile) -> None:
    """Host->device bandwidth: device_put of a few-MB array, warm median.
    Recorded as a probe (audit/bench attribution), not fitted into a
    routing constant directly — upload cost is already inside the measured
    device fixed/unit walls."""
    import jax

    buf = np.zeros((_TRANSFER_MB * 1024 * 1024 // 4,), dtype=np.float32)
    walls = []
    with obs.span("profile:probe.transfer", mb=_TRANSFER_MB):
        for _ in range(_WARM_REPS):
            t0 = time.perf_counter()
            jax.block_until_ready(jax.device_put(buf))
            walls.append(time.perf_counter() - t0)
    prof.probes["transfer_bytes_per_s"] = buf.nbytes / max(_median(walls), 1e-9)


def _probe_fused(b_pad: int, deadline: float) -> dict | None:
    """One fused-verb probe at batch width ``b_pad``: the exact deployment
    jit signature (prewarm derivation), dispatched through LocalExecutor —
    the real device boundary, chaos/cost/metrics included.  Returns
    {work, cold_s, warm_s, v, e, rows} or None when the deadline passed
    before this width started."""
    if time.perf_counter() >= deadline:
        return None
    from nemo_tpu.backend.jax_backend import LocalExecutor
    from nemo_tpu.models.case_studies import CASE_STUDIES
    from nemo_tpu.models.pipeline_model import BatchArrays
    from nemo_tpu.utils.prewarm import stress_signature

    family = sorted(CASE_STUDIES)[0]
    pre_p, post_p, static = stress_signature(family, _PROBE_RUNS, b_pad)
    arrays = {f"pre_{f}": getattr(pre_p, f) for f in BatchArrays.FIELDS} | {
        f"post_{f}": getattr(post_p, f) for f in BatchArrays.FIELDS
    }
    v, e = int(static["v"]), int(np.asarray(pre_p.edge_src).shape[1])
    ex = LocalExecutor()

    def dispatch() -> float:
        import jax

        obs.metrics.inc("profile.probe.dispatches")
        t0 = time.perf_counter()
        out = ex.run("fused", arrays, static, rows=_PROBE_RUNS)
        jax.block_until_ready([a for a in out.values() if a is not None])
        return time.perf_counter() - t0

    with obs.span("profile:probe.fused", b=b_pad, v=v, e=e):
        cold = dispatch()
        warm = []
        for _ in range(_WARM_REPS):
            if time.perf_counter() >= deadline:
                break
            warm.append(dispatch())
    return {
        "b": b_pad,
        "v": v,
        "e": e,
        "work": b_pad * (v + e),
        "cold_s": cold,
        "warm_s": _median(warm) if warm else cold,
        "arrays": (pre_p, post_p, static),
    }


def _probe_host(fused_probe: dict) -> dict:
    """Sparse-host wall on the SAME packed arrays as the widest fused
    probe — apples-to-apples work units for the crossover fit."""
    from nemo_tpu.ops.sparse_host import sparse_analysis_step

    pre_p, post_p, static = fused_probe["arrays"]
    walls = []
    with obs.span("profile:probe.sparse_host", b=fused_probe["b"]):
        for _ in range(_WARM_REPS):
            t0 = time.perf_counter()
            sparse_analysis_step(
                pre_p,
                post_p,
                v=int(static["v"]),
                pre_tid=int(static["pre_tid"]),
                post_tid=int(static["post_tid"]),
                num_tables=int(static["num_tables"]),
                comp_linear=bool(static.get("comp_linear", False)),
            )
            walls.append(time.perf_counter() - t0)
    return {"work": fused_probe["work"], "wall_s": _median(walls)}


def _flops_rate(fused_probe: dict) -> float | None:
    """Effective FLOPs/s from the cost table entry the probe dispatch just
    indexed (backend/jax_backend.py:_COST_BY_CLASS) over its warm wall —
    None when XLA cost analysis was unavailable for the signature."""
    from nemo_tpu.backend.jax_backend import _COST_BY_CLASS

    entry = _COST_BY_CLASS.get(("fused", fused_probe["v"], fused_probe["e"]))
    if entry is None:
        return None
    rec, rec_rows = entry
    if not rec.get("flops"):
        return None
    flops = float(rec["flops"]) / rec_rows * fused_probe["b"]
    return flops / max(fused_probe["warm_s"], 1e-9)


def _device_mem_mb() -> float | None:
    """25% of the smallest per-device bytes_limit on real accelerators
    (the dense-route watermark headroom); None on cpu — there the "device
    memory" is host RAM and the seeded watermark already encodes the
    giant-V escape economics."""
    import jax

    if jax.default_backend() == "cpu":
        return None
    limits = []
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:  # lint: allow-silent-except — memory_stats is optional per PJRT backend (docstring)
            stats = None
        if stats and stats.get("bytes_limit"):
            limits.append(int(stats["bytes_limit"]))
    if not limits:
        return None
    return min(limits) * 0.25 / 1e6


def run_calibration() -> PlatformProfile:
    """Run the probe suite and return the fitted (unsaved) profile."""
    budget = profile_budget_s()
    t_start = time.perf_counter()
    deadline = t_start + budget
    prof = PlatformProfile(platform_fingerprint())

    _probe_transfer(prof)

    points = []
    for b_pad in _PROBE_WIDTHS:
        p = _probe_fused(b_pad, deadline)
        if p is None:
            break
        points.append(p)
    if not points:
        raise RuntimeError(
            f"calibration budget ({budget:.1f}s) expired before the first "
            "fused probe completed"
        )
    host = _probe_host(points[-1])

    prof.probes["fused"] = [
        {k: v for k, v in p.items() if k != "arrays"} for p in points
    ]
    prof.probes["sparse_host"] = host
    prof.probes["compile_wall_s"] = max(p["cold_s"] - p["warm_s"] for p in points)

    host_unit = host["wall_s"] / max(host["work"], 1)
    if len(points) >= 2:
        dw = points[-1]["work"] - points[0]["work"]
        device_unit = max(
            (points[-1]["warm_s"] - points[0]["warm_s"]) / max(dw, 1), 1e-12
        )
    else:
        # Budget ran out after one width: keep the seeded slope, fit only
        # the intercept from the single measured point.
        device_unit = 5e-8
    device_fixed = max(
        points[0]["warm_s"] - device_unit * points[0]["work"], 1e-6
    )
    crossover = device_fixed / max(host_unit - device_unit, 1e-12)
    analysis_work = int(min(max(crossover, 1_000), 100_000_000))

    prof.set_constant("sched_host_unit", host_unit)
    prof.set_constant("sched_device_unit", device_unit, measured=len(points) >= 2)
    prof.set_constant("sched_device_fixed", device_fixed)
    prof.set_constant("sched_sparse_device_unit", device_unit * 5)
    prof.set_constant("analysis_host_work", analysis_work)
    prof.set_constant("synth_host_work", analysis_work)
    prof.set_constant("diff_host_work", min(analysis_work * 20, 2_000_000_000))

    rate = _flops_rate(points[-1])
    if rate is not None:
        prof.set_constant("sched_flops_per_s", rate)

    mem_mb = _device_mem_mb()
    if mem_mb is not None:
        prof.set_constant("sparse_device_mem_mb", mem_mb)
    else:
        prof.set_constant("sparse_device_mem_mb", 256.0, measured=False)
    prof.set_constant("sparse_device_density", 1.0 / 256.0, measured=False)

    prof.calibration_wall_s = time.perf_counter() - t_start
    obs.metrics.gauge("profile.calibration_s", prof.calibration_wall_s)
    _log.info(
        "profile.calibrated",
        wall_s=round(prof.calibration_wall_s, 3),
        host_unit=host_unit,
        device_unit=device_unit,
        device_fixed=device_fixed,
        analysis_host_work=analysis_work,
        points=len(points),
    )
    return prof
