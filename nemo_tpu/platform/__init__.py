"""Measured platform profiles (ISSUE 19): a bounded self-calibration
profiler plus the persistent, fingerprint-keyed profile that replaces the
hand-tuned routing defaults — env > measured profile > seeded defaults,
recorded per constant.  See platform/profile.py for the precedence and
invalidation contract, platform/calibrate.py for the probe suite."""

from .fingerprint import fingerprint_key, platform_fingerprint
from .profile import (
    PROFILE_ABI_VERSION,
    PlatformProfile,
    active_profile,
    constant_sources,
    ensure_calibrated,
    profile_mode,
    profile_value,
    reset_active_profile,
    telemetry_section,
)

__all__ = [
    "PROFILE_ABI_VERSION",
    "PlatformProfile",
    "active_profile",
    "constant_sources",
    "ensure_calibrated",
    "fingerprint_key",
    "platform_fingerprint",
    "profile_mode",
    "profile_value",
    "reset_active_profile",
    "telemetry_section",
]
