"""CLI: run a Dedalus protocol through the fault injector, emit Molly output.

    python -m nemo_tpu.dedalus -program specs/pb_asynchronous.ded \
        -EOT 6 -EFF 4 -crashes 0 -o out/
    python -m nemo_tpu.dedalus -spec pb_asynchronous -o out/   # bundled spec

Flag names mirror the Molly invocations recorded in the reference's
case-study headers (e.g. case-studies/pb_asynchronous.ded:2: --EOT 6
--EFF 4 --crashes 1 --nodes C,a,b,c).  The output directory feeds straight
into the debugger: python -m nemo_tpu.cli -faultInjOut <out>/<name>.
"""

from __future__ import annotations

import argparse
import os
import sys

from nemo_tpu.dedalus.faults import FaultSpec, write_molly_output
from nemo_tpu.dedalus.parser import load_program
from nemo_tpu.dedalus.registry import BUNDLED_SPECS, bundled_spec_path


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="nemo-tpu-dedalus", description="Mini-Dedalus fault injector (Molly stand-in)."
    )
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("-program", "--program", help="path to a .ded protocol spec")
    src.add_argument(
        "-spec",
        "--spec",
        choices=sorted(BUNDLED_SPECS),
        help="a bundled case-study spec (uses its recorded EOT/EFF/crashes "
        "defaults unless overridden)",
    )
    parser.add_argument("-EOT", "--eot", type=int, default=None, help="end of time (horizon)")
    parser.add_argument("-EFF", "--eff", type=int, default=None, help="end of finite failures")
    parser.add_argument("-crashes", "--crashes", type=int, default=None, help="max crashes")
    parser.add_argument("-o", "--out", default=".", help="output root directory")
    parser.add_argument(
        "-max-runs", "--max-runs", type=int, default=64, help="fault-run enumeration cap"
    )
    args = parser.parse_args(argv)

    if args.spec:
        path = bundled_spec_path(args.spec)
        defaults = BUNDLED_SPECS[args.spec]
        name = args.spec
    else:
        path = args.program
        defaults = FaultSpec()
        name = os.path.splitext(os.path.basename(path))[0]

    spec = FaultSpec(
        eot=args.eot if args.eot is not None else defaults.eot,
        eff=args.eff if args.eff is not None else defaults.eff,
        max_crashes=args.crashes if args.crashes is not None else defaults.max_crashes,
        max_runs=args.max_runs,
    )
    corpus = write_molly_output(load_program(path), spec, args.out, name)
    print(f"Molly-format output written to: {corpus}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
