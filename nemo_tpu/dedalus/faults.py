"""Bounded fault injection over a Dedalus program, Molly-output compatible.

Molly explores the crash/omission fault space of a protocol guided by
lineage (the reference consumes its output, README.md:5-8).  This stand-in
enumerates a bounded, deterministic fault space instead:

  run 0            the failure-free execution (the reference hardcodes run 0
                   as the good run, differential-provenance.go:26);
  omission runs    one per message observed in the failure-free trace with
                   send time < EFF (dropping it re-executes the protocol);
  crash runs       one per (node, crash time <= EFF) when max_crashes > 0,
                   for nodes that sent or received a message.

Each run re-executes the program under its fault assignment and is classified
success/fail by the pre ⇒ post invariant at EOT.  Output is a Molly-format
directory: runs.json, run_<i>_{pre,post}_provenance.json,
run_<i>_spacetime.dot (schema per faultinjectors/data-types.go:6-98; file
layout per faultinjectors/molly.go:18,59-60, hazard-analysis.go:25).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any

from nemo_tpu.obs import log as _obs_log

from .ast import Program
from .eval import Evaluator, FactInst, RunResult

_log = _obs_log.get_logger("nemo.dedalus")


@dataclass
class FaultSpec:
    eot: int = 6
    eff: int = 4
    max_crashes: int = 0
    nodes: list[str] | None = None
    max_runs: int = 256  # cap on enumerated fault runs (run 0 excluded)


@dataclass
class FaultRun:
    crashes: dict[str, int]
    omissions: set[tuple[str, str, int]]
    result: RunResult


def _condition_prov(result: RunResult, cond: str, eot: int) -> dict[str, Any]:
    """Provenance JSON of one condition: the derivation subgraph reachable
    from the condition table's goals; when the condition never held, fall
    back to the base facts' subgraph so the file is still meaningful."""
    roots = [
        result.derived[t].inst(cond, args)
        for t in range(1, eot + 1)
        for args in result.derived[t].facts(cond)
    ]
    if not roots:
        roots = [
            f
            for f in result.prov.goal_id
            if isinstance(f, FactInst)
            and f.time == 1
            and f.rel not in ("crash", "clock")
        ]
    return result.prov.extract(roots)


def _spacetime_dot(nodes: list[str], eot: int, run: FaultRun) -> str:
    """Space-time diagram via the shared builder (models/synth.py): local
    clock edges stop at a crash; only delivered messages draw arrows."""
    from nemo_tpu.models.synth import build_spacetime_dot

    messages = [
        {
            "from": m.src,
            "to": m.dst,
            "sendTime": m.send_time,
            "receiveTime": m.send_time + 1,
        }
        for m in run.result.messages
        if m.delivered
    ]
    return build_spacetime_dot(nodes, eot, messages, crashes=run.crashes)


def _infer_nodes(program: Program, runs: list[FaultRun]) -> list[str]:
    nodes: list[str] = []

    def add(n: str) -> None:
        if n and n not in nodes:
            nodes.append(n)

    for f in program.facts:
        if f.atom.args:
            add(f.atom.args[0].value)
    for r in runs:
        for m in r.result.messages:
            add(m.src)
            add(m.dst)
    return nodes


def enumerate_runs(program: Program, spec: FaultSpec) -> list[FaultRun]:
    """Run 0 failure-free, then one run per enumerated fault (bounded)."""
    base = Evaluator(program, spec.eot).run()
    runs = [FaultRun(crashes={}, omissions=set(), result=base)]

    # Enumeration order is coverage priority under the max_runs cap: the
    # linear classes (single omissions, single crashes) come before the
    # quadratic ones (omission pairs, crash x omission, crash pairs), so a
    # tight cap still explores every 1-fault execution before any 2-fault
    # combination displaces it.
    faults: list[tuple[dict[str, int], set[tuple[str, str, int]]]] = []
    singles: list[tuple[str, str, int]] = []
    for m in base.messages:
        key = (m.src, m.dst, m.send_time)
        if m.send_time < spec.eff and key not in singles:
            singles.append(key)
            faults.append(({}, {key}))
    crash_cands: list[tuple[str, int]] = []
    if spec.max_crashes > 0:
        nodes = _infer_nodes(program, runs)
        # Crash times start at 1: a node that is down from the very first
        # timestep is a reachable (and often the most violating) fault.
        crash_cands = [(n, tc) for n in nodes for tc in range(1, spec.eff + 1)]
        for n, tc in crash_cands:
            faults.append(({n: tc}, set()))
    # Pairs of omissions: protocols with redundancy (e.g. replication to two
    # backups) only fail when every copy is lost — single-fault enumeration
    # would never surface their violation.
    for i in range(len(singles)):
        for j in range(i + 1, len(singles)):
            faults.append(({}, {singles[i], singles[j]}))
    if spec.max_crashes > 0:
        # Crash x omission combinations: losses that redundancy absorbs only
        # become violations when the surviving holder also crashes.
        for n, tc in crash_cands:
            for key in singles:
                faults.append(({n: tc}, {key}))
        if spec.max_crashes >= 2:
            # Pairs of crashes on distinct nodes; violations that need two
            # replicas down are unreachable through single crashes.
            for i, (n1, t1) in enumerate(crash_cands):
                for n2, t2 in crash_cands[i + 1 :]:
                    if n1 != n2:
                        faults.append(({n1: t1, n2: t2}, set()))
        if spec.max_crashes > 2:
            _log.warning(
                "dedalus.max_crashes_capped",
                max_crashes=spec.max_crashes,
                detail="only single crashes and crash pairs are enumerated",
            )

    if len(faults) > spec.max_runs:
        _log.warning(
            "dedalus.fault_space_truncated",
            max_runs=spec.max_runs,
            enumerated=len(faults),
            detail="raise -max-runs to cover all",
        )
    for crashes, omissions in faults[: spec.max_runs]:
        result = Evaluator(program, spec.eot, crashes, omissions).run()
        runs.append(FaultRun(crashes=crashes, omissions=omissions, result=result))
    return runs


def write_molly_output(
    program: Program, spec: FaultSpec, out_dir: str, run_name: str
) -> str:
    """Execute the fault space and write a Molly-format output directory."""
    runs = enumerate_runs(program, spec)
    nodes = spec.nodes or _infer_nodes(program, runs)
    corpus = os.path.join(out_dir, run_name)
    os.makedirs(corpus, exist_ok=True)

    runs_json = []
    for i, run in enumerate(runs):
        res = run.result
        runs_json.append(
            {
                "iteration": i,
                "status": res.status,
                "failureSpec": {
                    "eot": spec.eot,
                    "eff": spec.eff,
                    "maxCrashes": spec.max_crashes,
                    "nodes": nodes,
                    "crashes": [
                        {"node": n, "time": t} for n, t in sorted(run.crashes.items())
                    ],
                    "omissions": [
                        {"from": s, "to": d, "time": t}
                        for s, d, t in sorted(run.omissions)
                    ],
                },
                "model": {"tables": {"pre": res.pre_rows, "post": res.post_rows}},
                "messages": [
                    {
                        "table": f"{m.rel}({', '.join(m.args)})",
                        "from": m.src,
                        "to": m.dst,
                        "sendTime": m.send_time,
                        "receiveTime": m.send_time + 1,
                    }
                    for m in res.messages
                    if m.delivered
                ],
            }
        )
        for cond in ("pre", "post"):
            path = os.path.join(corpus, f"run_{i}_{cond}_provenance.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(_condition_prov(res, cond, spec.eot), f, indent=1)
        with open(os.path.join(corpus, f"run_{i}_spacetime.dot"), "w", encoding="utf-8") as f:
            f.write(_spacetime_dot(nodes, spec.eot, run))

    with open(os.path.join(corpus, "runs.json"), "w", encoding="utf-8") as f:
        json.dump(runs_json, f, indent=1)
    return corpus
