"""Bottom-up Dedalus evaluation with provenance capture.

Synchronous-timestep semantics (the Molly execution model the reference's
case studies assume, see the invocation headers in case-studies/*.ded):

  * time advances 1..EOT; deductive rules reach a stratified fixpoint within
    each step; `@next` rules derive facts at t+1 on the same node; `@async`
    rules send a message delivered at t+1 (synchronous network) unless the
    fault model drops it;
  * a node crashed at tc sends nothing and receives nothing from tc on, and
    its `@next` state stops advancing — but facts elsewhere still mention it
    and the built-in `crash(N, N, Tc)` relation is visible at every step, so
    specs guard with `notin crash(...)` exactly like the reference's
    (case-studies/pb_asynchronous.ded:62-63);
  * an omission (src, dst, t) drops the message sent at t from src to dst.

Provenance: every derived fact instance is a goal node; every rule firing is
a rule node with edges head-goal -> rule -> body-goals (the reference's
DUETO orientation, graphing/pre-post-prov.go:150-195); async firings add the
`clock(src, dst, t, __WILDCARD__)` subgoal whose label carries the timestep
for the loader's regexes (faultinjectors/molly.go:76-89).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from itertools import count
from typing import Any, Iterable

from .ast import ASYNC, DEDUCTIVE, NEXT, Atom, Comparison, Program, Rule, Term

CRASH_REL = "crash"


@dataclass(frozen=True)
class FactInst:
    rel: str
    args: tuple[str, ...]
    time: int


@dataclass(frozen=True)
class SentMessage:
    rel: str
    args: tuple[str, ...]
    src: str
    dst: str
    send_time: int
    delivered: bool


class Provenance:
    """Derivation DAG over fact instances, in Molly JSON vocabulary."""

    def __init__(self) -> None:
        self._ids = count()
        self.goal_id: dict[FactInst, str] = {}
        self.goals: list[dict[str, Any]] = []
        self.rules: list[dict[str, Any]] = []
        self.edges: list[tuple[str, str]] = []
        self._firings: set[tuple] = set()

    def goal(self, fact: FactInst) -> str:
        gid = self.goal_id.get(fact)
        if gid is None:
            gid = f"goal_{next(self._ids)}"
            self.goal_id[fact] = gid
            self.goals.append(
                {
                    "id": gid,
                    "label": f"{fact.rel}({', '.join(fact.args)})",
                    "table": fact.rel,
                    "time": str(fact.time),
                }
            )
        return gid

    def clock_goal(self, src: str, dst: str, t: int) -> str:
        fact = FactInst("clock", (src, dst, str(t), "__WILDCARD__"), t)
        gid = self.goal_id.get(fact)
        if gid is None:
            gid = f"goal_{next(self._ids)}"
            self.goal_id[fact] = gid
            self.goals.append(
                {
                    "id": gid,
                    "label": f"clock({src}, {dst}, {t}, __WILDCARD__)",
                    "table": "clock",
                    "time": "",  # the loader extracts it from the label
                }
            )
        return gid

    def firing(
        self,
        head: FactInst,
        rule_table: str,
        rule_label: str,
        rule_type: str,
        bodies: Iterable[FactInst],
        clock: tuple[str, str, int] | None = None,
    ) -> None:
        bodies = tuple(bodies)
        key = (head, rule_table, rule_type, bodies, clock)
        if key in self._firings:
            return
        self._firings.add(key)
        rid = f"rule_{next(self._ids)}"
        self.rules.append({"id": rid, "label": rule_label, "table": rule_table, "type": rule_type})
        self.edges.append((self.goal(head), rid))
        for b in bodies:
            self.edges.append((rid, self.goal(b)))
        if clock is not None:
            self.edges.append((rid, self.clock_goal(*clock)))

    def extract(self, roots: list[FactInst]) -> dict[str, Any]:
        """The subgraph reachable from `roots` along goal->rule->goal edges,
        in Molly provenance-JSON shape."""
        out_edges: dict[str, list[str]] = {}
        for s, d in self.edges:
            out_edges.setdefault(s, []).append(d)
        keep: set[str] = set()
        stack = [self.goal_id[r] for r in roots if r in self.goal_id]
        while stack:
            node = stack.pop()
            if node in keep:
                continue
            keep.add(node)
            stack.extend(out_edges.get(node, ()))
        return {
            "goals": [g for g in self.goals if g["id"] in keep],
            "rules": [r for r in self.rules if r["id"] in keep],
            "edges": [
                {"from": s, "to": d} for s, d in self.edges if s in keep and d in keep
            ],
        }


class EvalError(ValueError):
    pass


def stratify(rules: list[Rule]) -> list[list[Rule]]:
    """Stratum numbers for DEDUCTIVE rules: a relation depending on another
    through negation or aggregation sits strictly above it.  @next/@async
    rules read the finished state of step t, so they are excluded here."""
    deductive = [r for r in rules if r.kind == DEDUCTIVE]
    stratum: dict[str, int] = {}
    for r in deductive:
        stratum.setdefault(r.head.rel, 0)
    for _ in range(len(deductive) * len(deductive) + 2):
        changed = False
        for r in deductive:
            need = 0
            for a in r.body:
                bump = 1 if r.is_aggregating else 0  # agg reads a closed stratum
                need = max(need, stratum.get(a.rel, 0) + bump)
            for a in r.negated:
                need = max(need, stratum.get(a.rel, 0) + 1)
            if need > stratum[r.head.rel]:
                if need > len(deductive) + 1:
                    raise EvalError(f"negation/aggregation cycle through {r.head.rel!r}")
                stratum[r.head.rel] = need
                changed = True
        if not changed:
            break
    else:
        raise EvalError("stratification did not converge")
    n = max(stratum.values(), default=0) + 1
    out: list[list[Rule]] = [[] for _ in range(n)]
    for r in deductive:
        out[stratum[r.head.rel]].append(r)
    return out


def _subst(term: Term, env: dict[str, str]) -> str | None:
    """Ground a term under env; None if an unbound var remains."""
    if term.kind == "const":
        return term.value
    if term.kind == "var":
        return env.get(term.name)
    if term.kind == "arith":
        v = env.get(term.name)
        if v is None:
            return None
        try:
            return str(int(v) + term.offset)
        except ValueError as ex:
            raise EvalError(f"arithmetic on non-integer {v!r}") from ex
    return None  # wild/agg never ground to a single value here


def _match(atom: Atom, fact_args: tuple[str, ...], env: dict[str, str]) -> dict[str, str] | None:
    if len(atom.args) != len(fact_args):
        return None
    new = dict(env)
    for term, val in zip(atom.args, fact_args):
        if term.kind == "wild":
            continue
        if term.kind == "const":
            if term.value != val:
                return None
        elif term.kind == "var":
            bound = new.get(term.name)
            if bound is None:
                new[term.name] = val
            elif bound != val:
                return None
        elif term.kind == "arith":
            bound = new.get(term.name)
            try:
                want = int(val) - term.offset
            except ValueError:
                return None
            if bound is None:
                new[term.name] = str(want)
            elif bound != str(want):
                return None
        else:
            return None
    return new


def _cmp_holds(c: Comparison, env: dict[str, str]) -> bool:
    left = _subst(c.left, env)
    right = _subst(c.right, env)
    if left is None or right is None:
        raise EvalError(f"comparison on unbound variable: {c}")
    try:
        lv: Any = int(left)
        rv: Any = int(right)
    except ValueError:
        lv, rv = left, right
    return {
        "!=": lv != rv,
        "==": lv == rv,
        ">": lv > rv,
        "<": lv < rv,
        ">=": lv >= rv,
        "<=": lv <= rv,
    }[c.op]


@dataclass
class StepState:
    """Facts visible at one timestep, indexed by relation."""

    by_rel: dict[str, set[tuple[str, ...]]] = field(default_factory=dict)
    src: dict[tuple[str, tuple[str, ...]], FactInst] = field(default_factory=dict)

    def add(self, fact: FactInst) -> bool:
        rel_set = self.by_rel.setdefault(fact.rel, set())
        if fact.args in rel_set:
            return False
        rel_set.add(fact.args)
        self.src[(fact.rel, fact.args)] = fact
        return True

    def facts(self, rel: str) -> list[tuple[str, ...]]:
        return sorted(self.by_rel.get(rel, ()))

    def inst(self, rel: str, args: tuple[str, ...]) -> FactInst:
        return self.src[(rel, args)]


@dataclass
class RunResult:
    derived: dict[int, StepState]
    prov: Provenance
    messages: list[SentMessage]
    pre_rows: list[list[str]]  # [args..., str(t)] rows, Model.Tables shape
    post_rows: list[list[str]]
    status: str  # "success" | "fail"


class Evaluator:
    def __init__(
        self,
        program: Program,
        eot: int,
        crashes: dict[str, int] | None = None,
        omissions: set[tuple[str, str, int]] | None = None,
    ) -> None:
        self.program = program
        self.eot = eot
        self.crashes = dict(crashes or {})
        self.omissions = set(omissions or ())
        self.strata = stratify(program.rules)
        self.next_rules = [r for r in program.rules if r.kind == NEXT]
        self.async_rules = [r for r in program.rules if r.kind == ASYNC]

    # ------------------------------------------------------------ helpers

    def _crashed(self, node: str, t: int) -> bool:
        tc = self.crashes.get(node)
        return tc is not None and t >= tc

    def _join(
        self, rule: Rule, state: StepState
    ) -> list[tuple[dict[str, str], list[FactInst]]]:
        """All satisfying bindings of the rule's body against one step, each
        with the body fact instances that actually produced it (in body-atom
        order) — so provenance edges cite the true supporting facts rather
        than a re-matched first-sorted candidate (which diverges under
        wildcards)."""
        envs: list[tuple[dict[str, str], list[FactInst]]] = [({}, [])]
        for atom in rule.body:
            nxt: list[tuple[dict[str, str], list[FactInst]]] = []
            for env, insts in envs:
                for args in state.facts(atom.rel):
                    new = _match(atom, args, env)
                    if new is not None:
                        nxt.append((new, [*insts, state.inst(atom.rel, args)]))
            envs = nxt
            if not envs:
                return []
        out = []
        for env, insts in envs:
            if any(self._neg_holds(a, state, env) for a in rule.negated):
                continue
            if all(_cmp_holds(c, env) for c in rule.comparisons):
                out.append((env, insts))
        return out

    def _neg_holds(self, atom: Atom, state: StepState, env: dict[str, str]) -> bool:
        for args in state.facts(atom.rel):
            if _match(atom, args, env) is not None:
                return True
        return False

    def _head_args(self, rule: Rule, env: dict[str, str]) -> tuple[str, ...] | None:
        vals = []
        for t in rule.head.args:
            v = _subst(t, env)
            if v is None:
                raise EvalError(
                    f"line {rule.line}: unbound variable in head of {rule.head.rel}"
                )
            vals.append(v)
        return tuple(vals)

    # --------------------------------------------------------------- run

    def run(self) -> RunResult:
        prov = Provenance()
        messages: list[SentMessage] = []
        derived: dict[int, StepState] = {t: StepState() for t in range(1, self.eot + 2)}

        # EDB facts: grounded at their stated time with a base firing; crash
        # facts are visible at every step (specs match `notin crash(..., _)`).
        for f in sorted(self.program.facts, key=lambda f: (f.atom.rel, f.time)):
            args = tuple(t.value for t in f.atom.args)
            node = args[0] if args else ""
            if f.time < 1:
                raise EvalError(f"fact {f.atom.rel} timed @{f.time}; time starts at 1")
            if f.time > self.eot or self._crashed(node, f.time):
                continue
            inst = FactInst(f.atom.rel, args, f.time)
            if derived[f.time].add(inst):
                prov.firing(inst, f.atom.rel, f.atom.rel, "", (), clock=(node, node, f.time))
        for node, tc in sorted(self.crashes.items()):
            for t in range(1, self.eot + 1):
                derived[t].add(FactInst(CRASH_REL, (node, node, str(tc)), t))

        for t in range(1, self.eot + 1):
            state = derived[t]
            # Deductive fixpoint, stratum by stratum.
            for stratum in self.strata:
                changed = True
                while changed:
                    changed = False
                    for rule in stratum:
                        if rule.is_aggregating:
                            changed |= self._fire_aggregate(rule, state, t, prov)
                            continue
                        for env, bodies in self._join(rule, state):
                            head = self._head_args(rule, env)
                            inst = FactInst(rule.head.rel, head, t)
                            if state.add(inst):
                                changed = True
                            prov.firing(
                                inst, rule.head.rel, rule.head.rel, "", bodies
                            )

            if t == self.eot:
                break

            # @next induction into t+1.
            for rule in self.next_rules:
                for env, bodies in self._join(rule, state):
                    head = self._head_args(rule, env)
                    node = head[0] if head else ""
                    if self._crashed(node, t + 1):
                        continue
                    inst = FactInst(rule.head.rel, head, t + 1)
                    derived[t + 1].add(inst)
                    prov.firing(
                        inst,
                        rule.head.rel,
                        f"{rule.head.rel}_next",
                        "next",
                        bodies,
                    )

            # @async messaging delivered at t+1.  The sender is the body's
            # location: Dedalus rule bodies are co-located (all positive
            # atoms share their first argument) — enforced here because a
            # mis-located body would silently defeat omission/crash faults.
            for rule in self.async_rules:
                for env, bodies in self._join(rule, state):
                    head = self._head_args(rule, env)
                    dst = head[0] if head else ""
                    locs = {b.args[0] for b in bodies if b.args}
                    if len(locs) > 1:
                        raise EvalError(
                            f"line {rule.line}: @async body atoms are not "
                            f"co-located (first arguments {sorted(locs)}); "
                            "route the triggering fact to the sending node "
                            "first"
                        )
                    src = bodies[0].args[0] if bodies and bodies[0].args else dst
                    dropped = (
                        self._crashed(src, t)
                        or self._crashed(dst, t + 1)
                        or (src, dst, t) in self.omissions
                    )
                    messages.append(
                        SentMessage(rule.head.rel, head, src, dst, t, not dropped)
                    )
                    if dropped:
                        continue
                    inst = FactInst(rule.head.rel, head, t + 1)
                    derived[t + 1].add(inst)
                    prov.firing(
                        inst,
                        rule.head.rel,
                        rule.head.rel,
                        "async",
                        bodies,
                        clock=(src, dst, t),
                    )

        pre_rows = [
            [*args, str(t)]
            for t in range(1, self.eot + 1)
            for args in derived[t].facts("pre")
        ]
        post_rows = [
            [*args, str(t)]
            for t in range(1, self.eot + 1)
            for args in derived[t].facts("post")
        ]
        # Invariant check at EOT (pre ⇒ post on the final step).
        final = derived[self.eot]
        violated = any(
            args not in final.by_rel.get("post", set())
            for args in final.by_rel.get("pre", set())
        )
        return RunResult(
            derived=derived,
            prov=prov,
            messages=messages,
            pre_rows=pre_rows,
            post_rows=post_rows,
            status="fail" if violated else "success",
        )

    def _fire_aggregate(self, rule: Rule, state: StepState, t: int, prov: Provenance) -> bool:
        """count<V> head aggregation: group by the non-agg head args over all
        body matches, count distinct V bindings."""
        groups: dict[tuple[str, ...], set[str]] = {}
        contributors: dict[tuple[str, ...], list[FactInst]] = {}
        agg_var = next(term.name for term in rule.head.args if term.kind == "agg")
        for env, bodies in self._join(rule, state):
            key = tuple(
                _subst(term, env) or "" for term in rule.head.args if term.kind != "agg"
            )
            val = env.get(agg_var)
            if val is None:
                raise EvalError(f"line {rule.line}: count<{agg_var}> variable unbound")
            groups.setdefault(key, set()).add(val)
            contributors.setdefault(key, []).extend(bodies)
        changed = False
        for key, vals in sorted(groups.items()):
            head = []
            it = iter(key)
            for term in rule.head.args:
                head.append(str(len(vals)) if term.kind == "agg" else next(it))
            inst = FactInst(rule.head.rel, tuple(head), t)
            if state.add(inst):
                changed = True
            seen: set[FactInst] = set()
            uniq = [b for b in contributors[key] if not (b in seen or seen.add(b))]
            prov.firing(inst, rule.head.rel, rule.head.rel, "", uniq)
        return changed
