"""AST for the Dedalus subset the case-study protocols use.

A Dedalus program is Datalog with an implicit logical-time attribute:
deductive rules close within a timestep, `@next` rules derive at t+1 on the
same node, `@async` rules deliver a message whose head location (first
argument) may differ from the body's.  See the Molly invocation headers in
the reference's case studies (e.g. case-studies/pb_asynchronous.ded:2).
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Rule temporal kinds.
DEDUCTIVE = ""
NEXT = "next"
ASYNC = "async"


@dataclass(frozen=True)
class Term:
    """One argument position.

    kind: "var" (capitalized identifier), "const" (quoted string or bare
    int), "wild" (`_`), "arith" (`Var+k`), or "agg" (`count<Var>`, head-only).
    """

    kind: str
    name: str = ""  # var name for var/arith/agg
    value: str = ""  # constant value (always stored as a string)
    offset: int = 0  # for arith: Var + offset

    def __repr__(self) -> str:  # compact, for error messages
        if self.kind == "var":
            return self.name
        if self.kind == "const":
            return repr(self.value)
        if self.kind == "wild":
            return "_"
        if self.kind == "arith":
            return f"{self.name}+{self.offset}"
        return f"count<{self.name}>"


@dataclass(frozen=True)
class Atom:
    rel: str
    args: tuple[Term, ...]

    def __repr__(self) -> str:
        return f"{self.rel}({', '.join(map(repr, self.args))})"


@dataclass(frozen=True)
class Comparison:
    """X op Y where each side is a var or a constant; numeric when both sides
    evaluate to integers, lexicographic otherwise."""

    op: str  # one of != == > < >= <=
    left: Term
    right: Term


@dataclass
class Rule:
    head: Atom
    kind: str  # DEDUCTIVE | NEXT | ASYNC
    body: list[Atom] = field(default_factory=list)  # positive atoms, in order
    negated: list[Atom] = field(default_factory=list)  # notin atoms
    comparisons: list[Comparison] = field(default_factory=list)
    line: int = 0  # source line, for error messages

    @property
    def is_aggregating(self) -> bool:
        return any(t.kind == "agg" for t in self.head.args)


@dataclass
class Fact:
    atom: Atom  # all-const args
    time: int  # the @<int> annotation


@dataclass
class Program:
    rules: list[Rule] = field(default_factory=list)
    facts: list[Fact] = field(default_factory=list)

    @property
    def relations(self) -> set[str]:
        rels = {f.atom.rel for f in self.facts}
        for r in self.rules:
            rels.add(r.head.rel)
            for a in r.body + r.negated:
                rels.add(a.rel)
        return rels
