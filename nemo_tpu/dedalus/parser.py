"""Parser for the Dedalus subset.

Grammar (statements end with `;`, `//` comments to end of line):

    fact:  rel(const, ...)@<int>;
    rule:  head(args) [@next|@async] :- body_elem, body_elem, ... ;
    body_elem: rel(args) | notin rel(args) | X != Y | X == Y
             | X > k | X < k | X >= k | X <= k
    args:  Var | "quoted" | bare-int | _ | Var+int | count<Var>

Variables are capitalized identifiers; relation names are lowercase.
"""

from __future__ import annotations

import re

from .ast import ASYNC, DEDUCTIVE, NEXT, Atom, Comparison, Fact, Program, Rule, Term

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<comment>//[^\n]*)
      | (?P<string>"(?:[^"\\]|\\.)*")
      | (?P<annot>@next\b|@async\b|@\d+)
      | (?P<entail>:-)
      | (?P<cmp>!=|==|>=|<=|>|<)
      | (?P<punct>[(),;_])
      | (?P<agg>count<[A-Za-z_][A-Za-z0-9_]*>)
      | (?P<plus>\+\d+)
      | (?P<int>-?\d+)
      | (?P<ident>[A-Za-z][A-Za-z0-9_]*)
    )""",
    re.VERBOSE,
)


class DedalusSyntaxError(ValueError):
    pass


def _tokenize(text: str) -> list[tuple[str, str, int]]:
    tokens: list[tuple[str, str, int]] = []
    pos, line = 0, 1
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == pos:
            if text[pos:].strip():
                raise DedalusSyntaxError(f"line {line}: cannot tokenize near {text[pos:pos+20]!r}")
            break
        line += text[pos : m.end()].count("\n")
        pos = m.end()
        if m.lastgroup == "comment":
            continue
        tokens.append((m.lastgroup, m.group(0).strip(), line))
    return tokens


class _Parser:
    def __init__(self, tokens: list[tuple[str, str, int]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> tuple[str, str, int]:
        if self.i >= len(self.toks):
            return ("eof", "", self.toks[-1][2] if self.toks else 0)
        return self.toks[self.i]

    def next(self) -> tuple[str, str, int]:
        tok = self.peek()
        self.i += 1
        return tok

    def expect(self, value: str) -> tuple[str, str, int]:
        tok = self.next()
        if tok[1] != value:
            raise DedalusSyntaxError(f"line {tok[2]}: expected {value!r}, got {tok[1]!r}")
        return tok

    def parse_term(self) -> Term:
        kind, val, line = self.next()
        if kind == "punct" and val == "_":
            return Term("wild")
        if kind == "string":
            return Term("const", value=val[1:-1].replace('\\"', '"'))
        if kind == "int":
            return Term("const", value=val)
        if kind == "agg":
            return Term("agg", name=val[len("count<") : -1])
        if kind == "ident":
            if val[0].isupper():
                nk, nv, _ = self.peek()
                if nk == "plus":
                    self.next()
                    return Term("arith", name=val, offset=int(nv[1:]))
                return Term("var", name=val)
            return Term("const", value=val)  # lowercase bare word = constant
        raise DedalusSyntaxError(f"line {line}: unexpected term {val!r}")

    def parse_atom(self, rel: str) -> Atom:
        self.expect("(")
        args: list[Term] = []
        while True:
            args.append(self.parse_term())
            kind, val, line = self.next()
            if val == ")":
                break
            if val != ",":
                raise DedalusSyntaxError(f"line {line}: expected ',' or ')', got {val!r}")
        return Atom(rel=rel, args=tuple(args))

    def parse_statement(self, prog: Program) -> None:
        kind, val, line = self.next()
        if kind != "ident" or not val[0].islower():
            raise DedalusSyntaxError(f"line {line}: expected relation name, got {val!r}")
        head = self.parse_atom(val)

        kind2, val2, line2 = self.next()
        if kind2 == "annot" and val2[1:].isdigit():  # fact
            time = int(val2[1:])
            self.expect(";")
            if any(t.kind != "const" for t in head.args):
                raise DedalusSyntaxError(f"line {line}: fact arguments must be constants")
            prog.facts.append(Fact(atom=head, time=time))
            return

        rule_kind = DEDUCTIVE
        if kind2 == "annot":
            rule_kind = NEXT if val2 == "@next" else ASYNC
            kind2, val2, line2 = self.next()
        if val2 != ":-":
            raise DedalusSyntaxError(f"line {line2}: expected ':-' or '@<time>;', got {val2!r}")

        rule = Rule(head=head, kind=rule_kind, line=line)
        while True:
            kind3, val3, line3 = self.next()
            if kind3 == "ident" and val3 == "notin":
                rk, rv, rl = self.next()
                if rk != "ident":
                    raise DedalusSyntaxError(f"line {rl}: expected relation after notin")
                rule.negated.append(self.parse_atom(rv))
            elif kind3 == "ident" and val3[0].islower() and self.peek()[1] == "(":
                rule.body.append(self.parse_atom(val3))
            else:
                # comparison: term op term
                self.i -= 1
                left = self.parse_term()
                ok, ov, ol = self.next()
                if ok != "cmp":
                    raise DedalusSyntaxError(f"line {ol}: expected comparison operator, got {ov!r}")
                right = self.parse_term()
                rule.comparisons.append(Comparison(op=ov, left=left, right=right))
            sep_kind, sep, sep_line = self.next()
            if sep == ";":
                break
            if sep != ",":
                raise DedalusSyntaxError(f"line {sep_line}: expected ',' or ';', got {sep!r}")
        prog.rules.append(rule)

    def parse(self) -> Program:
        prog = Program()
        while self.peek()[0] != "eof":
            self.parse_statement(prog)
        return prog


def parse_program(text: str) -> Program:
    return _Parser(_tokenize(text)).parse()


def load_program(path: str) -> Program:
    with open(path, "r", encoding="utf-8") as f:
        return parse_program(f.read())
