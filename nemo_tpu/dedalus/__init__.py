"""Mini-Dedalus: an executable stand-in for the Molly fault injector.

The reference consumes the output of Molly, an external Scala tool that
model-checks a Dedalus protocol under crash/omission faults and emits per-run
provenance graphs (reference: README.md:5-8).  Molly is not available in this
environment, so this package makes the framework self-contained: a parser and
bottom-up evaluator for the Dedalus subset the case-study protocols use
(deductive rules, @next induction, @async messaging, notin negation,
comparisons, head arithmetic, count<> aggregation), a provenance-capturing
interpreter, a bounded crash/omission fault injector, and a writer producing
Molly-format output directories (runs.json, run_<i>_{pre,post}_provenance.json,
run_<i>_spacetime.dot) that feed straight into nemo_tpu.ingest.molly.

    python -m nemo_tpu.dedalus -program <spec.ded> -EOT 6 -EFF 4 -o out/
"""

from nemo_tpu.dedalus.ast import Atom, Program, Rule, Term
from nemo_tpu.dedalus.parser import parse_program
