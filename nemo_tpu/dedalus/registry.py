"""Bundled case-study specs and their recorded fault-injection parameters.

One executable spec per reference case-study family (original formulations;
the reference records its Molly parameters in each file's header comment,
e.g. case-studies/pb_asynchronous.ded:2, MR-3858-hadoop.ded:2)."""

from __future__ import annotations

import os

from .faults import FaultSpec

_SPEC_DIR = os.path.join(os.path.dirname(__file__), "specs")

BUNDLED_SPECS: dict[str, FaultSpec] = {
    "pb_asynchronous": FaultSpec(eot=6, eff=4, max_crashes=0),
    "ca_2083_hinted_handoff": FaultSpec(eot=7, eff=4, max_crashes=1),
    "ca_2434_bootstrap_sync": FaultSpec(eot=8, eff=5, max_crashes=0),
    "mr_2995_failed_after_expiry": FaultSpec(eot=8, eff=5, max_crashes=0),
    "mr_3858_hadoop": FaultSpec(eot=6, eff=4, max_crashes=1),
    "zk_1270_racing_flag": FaultSpec(eot=6, eff=3, max_crashes=0),
}


def bundled_spec_path(name: str) -> str:
    return os.path.join(_SPEC_DIR, f"{name}.ded")
