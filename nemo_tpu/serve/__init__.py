"""Multi-tenant async serving tier (ISSUE 8).

The gRPC sidecar (service/server.py) was one-request-at-a-time; "millions
of users" means many concurrent sessions sharing one accelerator.  This
package is the policy layer the sidecar threads every work RPC through —
the same shape an LLM inference server puts in front of its model:

  * :mod:`nemo_tpu.serve.admission` — bounded admission queue with
    per-tenant round-robin fairness, a configurable in-flight cap,
    RESOURCE_EXHAUSTED + retry-after load shedding, and the graceful-drain
    flag the SIGTERM handler flips;
  * :mod:`nemo_tpu.serve.coalesce` — single-flight deduplication of
    concurrent identical requests, keyed on the result cache's content
    address (store segment fingerprints + config + ABI versions): N
    subscribers, ONE analysis, byte-identical responses (the dedup covers
    the dispatch/serialization; each request's ingest still runs — a
    milliseconds mmap against a warm corpus store);
  * :mod:`nemo_tpu.serve.batch` — cross-request continuous batching:
    compatible kernel dispatches from different in-flight requests merge
    into one padded device launch through ``parallel/sched.py``'s job
    queue, with per-request demux and rows-hinted cost accounting.

Streaming (the ``AnalyzeDirStream`` RPC) and the serving metrics
(``serve.*`` on the Prometheus surface) live in service/server.py, which
composes these three.  Import cost is tiny (numpy + obs); jax loads only
when a merged launch executes.

:mod:`nemo_tpu.serve.router` (ISSUE 14) adds the FLEET layer above all of
this: a thin consistent-hash router placing AnalyzeDir traffic by corpus
affinity over N replicas, with spill under load and failover — imported
lazily (it needs grpc), never from this package's top level.
"""

from __future__ import annotations

from .admission import (
    AdmissionController,
    AdmissionRejected,
    Ticket,
    controller,
    reset_controller,
    slo_snapshot,
)
from .batch import BATCHABLE_VERBS, KernelBatcher, batcher, reset_batcher
from .coalesce import SingleFlight, flights, reset_flights

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "BATCHABLE_VERBS",
    "KernelBatcher",
    "SingleFlight",
    "Ticket",
    "batcher",
    "controller",
    "flights",
    "reset_batcher",
    "reset_controller",
    "reset_flights",
    "slo_snapshot",
]
