"""Thin fleet router: content-affinity over N sidecar replicas (ISSUE 14).

One sidecar process tops out around ~19 req/s on this container (PR 8);
"millions of users" is a FLEET, and everything a fleet needs is already
content-addressed.  The router is the placement half of that story:

  * **Affinity routing** — ``AnalyzeDir`` / ``AnalyzeDirStream`` requests
    consistent-hash on the corpus's store identity (:func:`route_key` —
    the realpath the corpus store keys its store dir by,
    store/__init__.py:store_dir), so one corpus's coalesce leader,
    continuous batcher, and jit/compile cache naturally co-locate on one
    replica.  The ring uses virtual nodes: adding or removing one of N
    replicas remaps ~K/N keys, not the whole fleet.
  * **Spill under load** — the existing admission/backpressure signals
    drive it: a home replica that sheds (RESOURCE_EXHAUSTED with the
    ``nemo-retry-after-s`` hint) or whose last-polled queue depth crosses
    ``NEMO_ROUTER_SPILL_DEPTH`` sends the request to the least-loaded
    live replica instead (``router.spill``).  The shared rcache tier makes
    this safe: any replica serves any warm corpus.
  * **Failover** — UNAVAILABLE marks the replica down and retries the
    next replica on the ring after a jittered pause
    (utils/backoff.py:FAILOVER_POLICY), counted ``router.failover``; a
    background Health poll (``NEMO_ROUTER_HEALTH_S``) brings recovered
    replicas back into rotation.
  * **Byte transparency** — generic gRPC handlers with identity
    serializers hand the router raw request bytes, which it forwards
    verbatim (AnalyzeDir's JSON is peeked at only for the routing key);
    trailing metadata (rcache/coalesce/fleet statuses, retry-after hints,
    span payloads) rides back untouched.  A router hop costs network +
    bytes-plumbing, never a protobuf decode.

RPCs with no content identity (Analyze, AnalyzeStream, Kernel, Health) go
to the least-loaded live replica.  Run it with the sidecar CLI:
``python -m nemo_tpu.service.server --router --backends host:p1,host:p2``
(or ``NEMO_FLEET_REPLICAS``).
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
import time

import grpc

from nemo_tpu import obs
from nemo_tpu.obs import log as obs_log
from nemo_tpu.serve.autoscale import Autoscaler
from nemo_tpu.utils.backoff import FAILOVER_POLICY
from nemo_tpu.utils.env import env_float

_log = obs_log.get_logger("nemo.router")

#: Same cap as the replica's per-RPC span relay (service/server.py): a
#: stitched span payload past this rides without the router's additions.
_SPANS_MAX_BYTES = 1 << 20

#: Same service name the replicas register (service/server.py) — the
#: router is indistinguishable from a replica to every existing client.
SERVICE = "nemo.NemoAnalysis"


def ring_hash(s: str) -> int:
    """Stable 64-bit ring position (sha256 prefix — never Python's
    salted hash(), which would reshuffle the fleet every process)."""
    return int.from_bytes(hashlib.sha256(s.encode("utf-8")).digest()[:8], "big")


def route_key(molly_dir: str) -> str:
    """A corpus's ROUTING identity: the realpath — exactly what the corpus
    store keys its store dir by (store/__init__.py:store_dir), i.e. the
    store's identity.  Stable across corpus growth, so a grown corpus
    keeps its leader/batcher/compile-cache affinity while the segment
    fingerprints (the rcache content address) handle freshness."""
    return os.path.realpath(molly_dir)


class HashRing:
    """Consistent-hash ring with virtual nodes.

    Each backend owns ``vnodes`` points; a key routes to the first point
    at or after its own hash (wrapping).  Adding one backend to N claims
    ~1/(N+1) of every other backend's keyspace (the classic remap bound);
    removing one hands its keys to ring successors — nobody else moves.
    """

    def __init__(self, backends: list[str], vnodes: int = 64) -> None:
        self.backends = list(dict.fromkeys(backends))
        self.vnodes = int(vnodes)
        ring = sorted(
            (ring_hash(f"{b}#{i}"), b)
            for b in self.backends
            for i in range(self.vnodes)
        )
        self._ring = ring
        self._points = [p for p, _ in ring]

    def preference(self, key: str) -> list[str]:
        """Every backend, ordered by the ring walk from ``key``'s point:
        [0] is the affinity home, the rest are the failover order (each
        distinct backend in walk order)."""
        if not self._ring:
            return []
        i = bisect.bisect(self._points, ring_hash(key)) % len(self._ring)
        seen: set[str] = set()
        out: list[str] = []
        for k in range(len(self._ring)):
            b = self._ring[(i + k) % len(self._ring)][1]
            if b not in seen:
                seen.add(b)
                out.append(b)
                if len(out) == len(self.backends):
                    break
        return out

    def route(self, key: str) -> str:
        return self.preference(key)[0]


def spill_depth_default() -> float:
    """Queue depth (queued + inflight, from the replica's own gauges) past
    which the router proactively spills an affinity-routed request to the
    least-loaded replica (``NEMO_ROUTER_SPILL_DEPTH``, default 8)."""
    return env_float("NEMO_ROUTER_SPILL_DEPTH", 8.0)


class Router:
    """Routing state + forwarding engine behind the proxy handlers.

    The decision core (:meth:`plan`) is pure state→order and unit-testable
    without gRPC; the forwarding methods do the wire work.  Load is
    tracked two ways: the router's own in-flight count per backend
    (exact, request-scoped) plus the last Health poll's queued+inflight
    gauges (covers load arriving from OTHER routers/direct clients).
    """

    def __init__(self, backends: list[str], vnodes: int = 64) -> None:
        if not backends:
            raise ValueError("router needs at least one backend replica")
        self.ring = HashRing(backends, vnodes)
        self.backends = self.ring.backends
        self._lock = threading.Lock()
        self._channels: dict[str, grpc.Channel] = {}
        self._inflight = {b: 0 for b in self.backends}
        self._depth = {b: 0.0 for b in self.backends}
        self._up = {b: True for b in self.backends}
        # Full per-replica metrics snapshot from the last Health round —
        # the federation/autoscale source of truth ({} until first reply).
        self._snaps: dict[str, dict] = {b: {} for b in self.backends}
        self.autoscaler = Autoscaler()
        self._stop = threading.Event()
        self._health_thread: threading.Thread | None = None

    # ------------------------------------------------------------- state

    def start(self) -> None:
        """Begin the background Health poll (idempotent)."""
        if self._health_thread is not None:
            return
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="nemo-router-health"
        )
        self._health_thread.start()

    def stop(self) -> None:
        self._stop.set()
        # Join the poll thread BEFORE closing channels: a pass racing this
        # stop could otherwise recreate a channel after the map is cleared
        # and leak it (plus its grpc worker threads) until process exit.
        t = self._health_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10.0)
        with self._lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            ch.close()

    def _health_loop(self) -> None:
        period = max(0.2, env_float("NEMO_ROUTER_HEALTH_S", 2.0))
        while not self._stop.wait(period):
            self.poll_health()

    def poll_health(self) -> None:
        """One Health round across the fleet: marks replicas up/down and
        refreshes their queued+inflight depth from the metrics snapshot
        that rides every Health response's trailing metadata."""
        from nemo_tpu.service.proto import nemo_service_pb2 as pb

        req = pb.HealthRequest().SerializeToString()
        for b in self.backends:
            depth = 0.0
            snap: dict = {}
            try:
                method = self._channel(b).unary_unary(f"/{SERVICE}/Health")
                _, call = method.with_call(req, timeout=5.0)
                md = dict(call.trailing_metadata() or ())
                raw = md.get("nemo-metrics-bin")
                if raw:
                    snap = json.loads(
                        raw.decode("utf-8") if isinstance(raw, bytes) else raw
                    )
                    gauges = snap.get("gauges", {})
                    depth = float(gauges.get("serve.queue_depth", 0.0)) + float(
                        gauges.get("serve.inflight", 0.0)
                    )
                up = True
            except Exception:
                up = False
            with self._lock:
                was_up = self._up[b]
                self._up[b] = up
                self._depth[b] = depth if up else 0.0
                if up:
                    # A down replica keeps its LAST snapshot (the federated
                    # page marks it down via nemo_fleet_backend_up; stale
                    # series beat vanishing series mid-incident).
                    self._snaps[b] = snap
            if up != was_up:
                obs.metrics.inc("router.backend_up" if up else "router.backend_down")
                _log.warning("router.backend_state", backend=b, up=up)
            obs.metrics.gauge(
                f"router.backend.{self.backends.index(b)}.up", 1.0 if up else 0.0
            )
        snaps, up_map = self.fleet_snapshots()
        rec = self.autoscaler.update(snaps, up_map)
        obs.metrics.gauge("fleet.autoscale.recommendation", rec)

    def fleet_snapshots(self) -> tuple[dict, dict]:
        """(target -> last Health-ride metrics snapshot, target -> up) —
        what the federated /metrics page and the autoscaler consume."""
        with self._lock:
            return (
                {b: self._snaps.get(b) or {} for b in self.backends},
                dict(self._up),
            )

    def fleet_health_trailing(self, tm, backend: str):
        """Health trailing-metadata hook: replace the ONE forwarded
        replica's ``nemo-metrics-bin`` snapshot with the whole fleet's —
        ``{"replicas": {target: snapshot}, "up": {target: bool}}`` — so
        ``client.health()["metrics"]`` through the router describes every
        replica instead of whichever replica answered.  The answering
        replica's snapshot is taken fresh from this very response; the
        rest come from the last Health poll round."""
        snaps, up = self.fleet_snapshots()
        out = []
        for k, v in tm or ():
            if k == "nemo-metrics-bin":
                try:
                    snaps[backend] = json.loads(
                        v.decode("utf-8") if isinstance(v, bytes) else v
                    )
                    up[backend] = True
                except Exception:  # lint: allow-silent-except — stale poll snapshot stands in
                    pass
                continue
            out.append((k, v))
        doc = {"replicas": snaps, "up": up}
        out.append(("nemo-metrics-bin", json.dumps(doc).encode("utf-8")))
        return tuple(out)

    def _channel(self, b: str) -> grpc.Channel:
        with self._lock:
            ch = self._channels.get(b)
        if ch is not None:
            return ch
        # The environment quirk (utils/subproc.py): a channel created
        # before its server listens wedges.  ONE connect probe — a closed
        # port refuses instantly, so a down backend costs microseconds
        # (failover / the next health round retries), not a 5 s polling
        # stall per request and per poll_health pass.
        import socket as _socket

        host, _, port = b.rpartition(":")
        _socket.create_connection((host or "127.0.0.1", int(port)), 2.0).close()
        ch = grpc.insecure_channel(
            b,
            options=[
                ("grpc.max_receive_message_length", 1 << 30),
                ("grpc.max_send_message_length", 1 << 30),
                ("grpc.max_metadata_size", 2 << 20),
            ],
        )
        with self._lock:
            if b in self._channels:
                ch.close()
                return self._channels[b]
            self._channels[b] = ch
        return ch

    def _begin(self, b: str) -> None:
        with self._lock:
            self._inflight[b] += 1
        obs.metrics.gauge("router.inflight", sum(self._inflight.values()))

    def _end(self, b: str) -> None:
        with self._lock:
            self._inflight[b] = max(0, self._inflight[b] - 1)
        obs.metrics.gauge("router.inflight", sum(self._inflight.values()))

    def _mark_down(self, b: str) -> None:
        with self._lock:
            was = self._up[b]
            self._up[b] = False
        if was:
            obs.metrics.inc("router.backend_down")
            _log.warning("router.backend_state", backend=b, up=False)

    def backend_states(self) -> dict:
        with self._lock:
            return {
                b: {
                    "up": self._up[b],
                    "inflight": self._inflight[b],
                    "depth": self._depth[b],
                }
                for b in self.backends
            }

    # ------------------------------------------------------------ routing

    def plan(self, key: str | None) -> list[str]:
        """The ordered backends to try for one request.

        No key (Analyze/Kernel/Health): least-loaded live replicas first.
        With a key: the ring's affinity order, except (a) replicas marked
        down sink to the tail (they are still TRIED last — the Health poll
        may be stale), and (b) when the live home's load is at/over the
        spill threshold AND a strictly less-loaded live replica exists,
        that replica is tried first (``router.spill_planned``)."""
        with self._lock:
            up = dict(self._up)
            # max, not sum: the replica's polled serve.inflight gauge
            # already INCLUDES requests this router forwarded, so summing
            # would double-count them and trip the spill threshold at half
            # its configured depth.  The router's own count is live; the
            # poll covers load arriving from elsewhere.
            load = {
                b: max(self._inflight[b], self._depth[b]) for b in self.backends
            }
        if key is None:
            return sorted(self.backends, key=lambda b: (not up[b], load[b]))
        pref = self.ring.preference(key)
        alive = [b for b in pref if up[b]]
        down = [b for b in pref if not up[b]]
        order = alive + down
        if alive:
            home = alive[0]
            if load[home] >= spill_depth_default():
                spill = min(
                    (b for b in alive if b != home),
                    key=lambda b: load[b],
                    default=None,
                )
                if spill is not None and load[spill] < load[home]:
                    obs.metrics.inc("router.spill_planned")
                    order = [spill] + [b for b in order if b != spill]
        return order

    # --------------------------------------------------------- forwarding

    @staticmethod
    def _retry_hint(ex: grpc.RpcError):
        """The ``nemo-retry-after-s`` trailing value of an admission
        rejection, or None — the discriminator between "replica is
        shedding load" (spill) and a deterministic RESOURCE_EXHAUSTED
        (propagate; the client precedent in service/client.py:_call)."""
        try:
            for k, v in ex.trailing_metadata() or ():
                if k == "nemo-retry-after-s":
                    return v
        except Exception:
            return None
        return None

    @staticmethod
    def _fwd_metadata(context) -> tuple:
        """The client's metadata (tenant, trace id), forwarded verbatim."""
        return tuple(context.invocation_metadata() or ()) if context is not None else ()

    @staticmethod
    def _timeout_of(context) -> float | None:
        """Forwarded deadline: the client's remaining time, or None (no
        deadline) when the client set none — the router must not impose a
        bound of its own on a cold first-compile analysis that would
        succeed direct-to-replica."""
        if context is not None:
            t = context.time_remaining()
            if t is not None and t > 0:
                return t
        return None

    def _abort_like(self, context, ex: grpc.RpcError, rpc: str):
        """Propagate a backend's terminal status verbatim (trailing
        metadata included — retry-after hints must survive the hop)."""
        try:
            tm = ex.trailing_metadata()
            if tm and context is not None:
                context.set_trailing_metadata(tuple(tm))
        except Exception:  # lint: allow-silent-except — best-effort metadata relay
            pass
        obs.metrics.inc(f"router.errors.{rpc}")
        context.abort(ex.code(), ex.details() or f"{rpc} failed on every replica")

    # ----------------------------------------------------- trace stitching

    @staticmethod
    def _trace_id_of(md: tuple) -> str | None:
        for k, v in md:
            if k == "nemo-trace-id":
                return v if isinstance(v, str) else v.decode("utf-8", "replace")
        return None

    @staticmethod
    def _stitch_trailing(tm, spans: list[dict]):
        """Merge the router's own forward spans into the replica's
        ``nemo-spans-bin`` trailing payload (wire shape:
        Tracer.drain_spans dicts) so the tracing client adopts ONE stitched
        set — replica spans under the replica's pid, router spans under
        ours.  Oversize payloads ride through without the additions (same
        cap stance as the replica's _SpanCollection)."""
        if not spans:
            return tm
        out = []
        payload: list = []
        for k, v in tm or ():
            if k == "nemo-spans-bin":
                try:
                    payload = json.loads(v.decode("utf-8") if isinstance(v, bytes) else v)
                except Exception:
                    payload = []
                continue
            out.append((k, v))
        payload = list(payload) + spans
        blob = json.dumps(payload).encode("utf-8")
        if len(blob) <= _SPANS_MAX_BYTES:
            out.append(("nemo-spans-bin", blob))
        return tuple(out)

    def _forward_span(
        self, rpc: str, backend: str, start_us: int, dur_us: int, attempt: int
    ) -> dict:
        """One router-hop span in the cross-process wire shape `adopt`
        consumes.  Also lands in the armed flight recorder's ring (and the
        router's own tracer, were one active)."""
        args = {"backend": backend, "attempt": attempt}
        obs.add_span(f"router:{rpc}", start_us, dur_us, args)
        return {
            "name": f"router:{rpc}",
            "ts": start_us,
            "dur": dur_us,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "thread_name": threading.current_thread().name,
            "args": args,
        }

    def call_unary(
        self,
        rpc: str,
        request: bytes,
        context,
        key: str | None = None,
        trailing_hook=None,
    ) -> bytes:
        """Forward one unary RPC: affinity plan, reactive spill on a
        shedding home, jittered failover on UNAVAILABLE.  `trailing_hook`
        (tm, backend) -> tm lets a handler rewrite the trailing metadata
        before relay (the Health handler swaps in the fleet snapshot)."""
        obs.metrics.inc(f"router.requests.{rpc}")
        md = self._fwd_metadata(context)
        client_tid = self._trace_id_of(md)
        timeout = self._timeout_of(context)
        backoff = FAILOVER_POLICY.session()
        candidates = self.plan(key)
        last: grpc.RpcError | None = None
        for i, b in enumerate(candidates):
            try:
                ch = self._channel(b)
            except Exception:
                self._mark_down(b)
                obs.metrics.inc("router.failover")
                continue
            method = ch.unary_unary(f"/{SERVICE}/{rpc}")
            self._begin(b)
            try:
                start_us = time.perf_counter_ns() // 1000
                resp, call = method.with_call(
                    request, metadata=md or None, timeout=timeout
                )
                dur_us = time.perf_counter_ns() // 1000 - start_us
                tm = call.trailing_metadata() or ()
                if client_tid is not None:
                    tm = self._stitch_trailing(
                        tm, [self._forward_span(rpc, b, start_us, dur_us, i)]
                    )
                else:
                    self._forward_span(rpc, b, start_us, dur_us, i)
                if trailing_hook is not None:
                    tm = trailing_hook(tm, b)
                if tm and context is not None:
                    context.set_trailing_metadata(tuple(tm))
                obs.metrics.inc(f"router.routed.{rpc}")
                if i > 0:
                    obs.metrics.inc("router.rerouted")
                return resp
            except grpc.RpcError as ex:
                code = ex.code()
                if code == grpc.StatusCode.UNAVAILABLE and i + 1 < len(candidates):
                    self._mark_down(b)
                    obs.metrics.inc("router.failover")
                    last = ex
                    wait = backoff.delay()
                    if wait is None:
                        break
                    time.sleep(wait)
                    continue
                if (
                    code == grpc.StatusCode.RESOURCE_EXHAUSTED
                    and self._retry_hint(ex) is not None
                    and i + 1 < len(candidates)
                ):
                    # The home replica is SHEDDING (admission rejection
                    # with a retry-after hint): spill to the next
                    # candidate instead of bouncing the client.
                    obs.metrics.inc("router.spill")
                    last = ex
                    continue
                self._abort_like(context, ex, rpc)
            finally:
                self._end(b)
        if last is not None:
            self._abort_like(context, last, rpc)
        obs.metrics.inc(f"router.errors.{rpc}")
        context.abort(grpc.StatusCode.UNAVAILABLE, f"no replica reachable for {rpc}")

    def call_server_stream(self, rpc: str, request: bytes, context, key: str | None = None):
        """Forward a server-streaming RPC.  Failover only while nothing
        has been yielded (the replay-safe window — the client-side stream
        retry precedent, service/client.py:analyze_dir_stream)."""
        obs.metrics.inc(f"router.requests.{rpc}")
        md = self._fwd_metadata(context)
        client_tid = self._trace_id_of(md)
        timeout = self._timeout_of(context)
        backoff = FAILOVER_POLICY.session()
        candidates = self.plan(key)
        last: grpc.RpcError | None = None
        for b in candidates:
            try:
                ch = self._channel(b)
            except Exception:
                self._mark_down(b)
                obs.metrics.inc("router.failover")
                continue
            method = ch.unary_stream(f"/{SERVICE}/{rpc}")
            self._begin(b)
            got_any = False
            try:
                start_us = time.perf_counter_ns() // 1000
                stream = method(request, metadata=md or None, timeout=timeout)
                for item in stream:
                    got_any = True
                    yield item
                dur_us = time.perf_counter_ns() // 1000 - start_us
                try:
                    tm = stream.trailing_metadata() or ()
                    span = self._forward_span(rpc, b, start_us, dur_us, 0)
                    if client_tid is not None:
                        tm = self._stitch_trailing(tm, [span])
                    if tm and context is not None:
                        context.set_trailing_metadata(tuple(tm))
                except Exception:  # lint: allow-silent-except — best-effort metadata relay
                    pass
                obs.metrics.inc(f"router.routed.{rpc}")
                return
            except grpc.RpcError as ex:
                if (
                    not got_any
                    and ex.code() == grpc.StatusCode.UNAVAILABLE
                ):
                    self._mark_down(b)
                    obs.metrics.inc("router.failover")
                    last = ex
                    wait = backoff.delay()
                    if wait is None:
                        break
                    time.sleep(wait)
                    continue
                self._abort_like(context, ex, rpc)
            finally:
                self._end(b)
        if last is not None:
            self._abort_like(context, last, rpc)
        obs.metrics.inc(f"router.errors.{rpc}")
        context.abort(grpc.StatusCode.UNAVAILABLE, f"no replica reachable for {rpc}")

    def call_stream_stream(self, rpc: str, request_iterator, context):
        """Forward a bidi stream to the least-loaded live replica.  No
        failover: the request iterator is consumed as it forwards, so a
        mid-stream replay would double-dispatch — errors propagate and the
        client's own replay-safe retry handles the cold window."""
        obs.metrics.inc(f"router.requests.{rpc}")
        md = self._fwd_metadata(context)
        timeout = self._timeout_of(context)
        for b in self.plan(None):
            try:
                ch = self._channel(b)
            except Exception:
                self._mark_down(b)
                obs.metrics.inc("router.failover")
                continue
            method = ch.stream_stream(f"/{SERVICE}/{rpc}")
            self._begin(b)
            try:
                stream = method(request_iterator, metadata=md or None, timeout=timeout)
                for item in stream:
                    yield item
                try:
                    tm = stream.trailing_metadata()
                    if tm and context is not None:
                        context.set_trailing_metadata(tuple(tm))
                except Exception:  # lint: allow-silent-except — best-effort metadata relay
                    pass
                obs.metrics.inc(f"router.routed.{rpc}")
                return
            except grpc.RpcError as ex:
                self._abort_like(context, ex, rpc)
            finally:
                self._end(b)
        obs.metrics.inc(f"router.errors.{rpc}")
        context.abort(grpc.StatusCode.UNAVAILABLE, f"no replica reachable for {rpc}")


def _dir_key_of(request: bytes) -> str | None:
    """Peek the routing key out of an AnalyzeDir/AnalyzeDirStream JSON
    request (the ONLY inspection the router does).  Unparseable requests
    route by load and let the replica return the proper
    INVALID_ARGUMENT."""
    try:
        doc = json.loads(request.decode("utf-8"))
        d = doc.get("dir") or (doc.get("dirs") or [None])[0]
        return route_key(d) if isinstance(d, str) and d else None
    except Exception:
        return None


def make_router_server(
    port: int, backends: list[str], max_workers: int = 64, vnodes: int = 64
) -> tuple[grpc.Server, int, Router]:
    """Build (but don't start) the router server: the same NemoAnalysis
    surface the replicas expose, registered with IDENTITY serializers so
    every handler sees raw bytes and forwards them verbatim."""
    from concurrent import futures

    router = Router(backends, vnodes=vnodes)
    router.start()

    def unary(rpc: str, keyed: bool = False, trailing_hook=None):
        def handler(request: bytes, context):
            key = _dir_key_of(request) if keyed else None
            return router.call_unary(
                rpc, request, context, key=key, trailing_hook=trailing_hook
            )

        return grpc.unary_unary_rpc_method_handler(handler)

    def server_stream(rpc: str, keyed: bool = False):
        def handler(request: bytes, context):
            key = _dir_key_of(request) if keyed else None
            yield from router.call_server_stream(rpc, request, context, key=key)

        return grpc.unary_stream_rpc_method_handler(handler)

    handlers = {
        "Health": unary("Health", trailing_hook=router.fleet_health_trailing),
        "Analyze": unary("Analyze"),
        "Kernel": unary("Kernel"),
        "AnalyzeDir": unary("AnalyzeDir", keyed=True),
        "AnalyzeDirStream": server_stream("AnalyzeDirStream", keyed=True),
        "AnalyzeStream": grpc.stream_stream_rpc_method_handler(
            lambda it, ctx: router.call_stream_stream("AnalyzeStream", it, ctx)
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", 1 << 30),
            ("grpc.max_send_message_length", 1 << 30),
            ("grpc.max_metadata_size", 2 << 20),
        ],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    return server, bound, router
