"""Fleet autoscale signal: queue-depth/inflight/shed-rate -> a hysteresis
recommendation a k8s HPA (or a human) can act on.

Closes the ROADMAP item-1 remainder: the admission tier has exposed
``serve.queue_depth`` / ``serve.inflight`` gauges since PR 8, but nothing
turned them into an actionable scaling signal.  The router already polls
every backend's Health RPC and keeps the full metrics snapshot each reply
carries; this module folds those snapshots into one integer:

    +1  scale up    (sustained utilization above the high watermark, or
                     any shedding observed — a shed IS the queue saying no)
     0  hold
    -1  scale down  (sustained utilization below the low watermark, no
                     shedding, and the post-flip cooldown has passed)

**Contract** (documented for HPA consumption — README "Fleet
observability"): the recommendation is exposed as the
``nemo_fleet_autoscale_recommendation`` gauge on the router's federated
``/metrics`` and as JSON on its ``/autoscale`` endpoint::

    {"recommendation": -1|0|1, "desired_replicas": N, "replicas_live": N,
     "utilization": float, "queue_depth": float, "inflight": float,
     "capacity": float, "shed_delta": float, "reason": str,
     "thresholds": {...}}

``desired_replicas`` is ``max(1, replicas_live + recommendation)`` —
feed it to an external-metrics HPA directly.

**Hysteresis** (so a bursty queue doesn't flap the fleet): utilization is
``(queue_depth + inflight) / capacity`` summed over live replicas, with
capacity per replica from its ``serve.capacity`` gauge (the admission
max-inflight; default 4 when a replica predates the gauge).  An up signal
must hold for ``NEMO_AUTOSCALE_HOLD_UP`` consecutive polls (default 2 —
scaling up is cheap, starving users is not); a down/neutral transition
must hold for ``NEMO_AUTOSCALE_HOLD_DOWN`` polls (default 5) AND sit out
``NEMO_AUTOSCALE_COOLDOWN_S`` (default 60 s) after the last flip.
Watermarks: ``NEMO_AUTOSCALE_UP`` (default 0.8) / ``NEMO_AUTOSCALE_DOWN``
(default 0.2).  All knobs are warn-and-default via utils/env.

Pure state machine over fed samples — no I/O, no threads — so the
hysteresis is unit-testable without a fleet (tests/test_obs_fleet.py).
"""

from __future__ import annotations

import time

from ..utils.env import env_float, env_int

__all__ = ["Autoscaler", "DEFAULT_CAPACITY"]

#: Assumed per-replica admission capacity when a replica's snapshot lacks
#: the ``serve.capacity`` gauge (replicas from before this PR).
DEFAULT_CAPACITY = 4.0


class Autoscaler:
    """Feed `update()` once per router health-poll round; read `doc()`."""

    def __init__(
        self,
        up_util: float | None = None,
        down_util: float | None = None,
        hold_up: int | None = None,
        hold_down: int | None = None,
        cooldown_s: float | None = None,
    ) -> None:
        self.up_util = up_util if up_util is not None else env_float("NEMO_AUTOSCALE_UP", 0.8)
        self.down_util = (
            down_util if down_util is not None else env_float("NEMO_AUTOSCALE_DOWN", 0.2)
        )
        self.hold_up = hold_up if hold_up is not None else env_int("NEMO_AUTOSCALE_HOLD_UP", 2)
        self.hold_down = (
            hold_down if hold_down is not None else env_int("NEMO_AUTOSCALE_HOLD_DOWN", 5)
        )
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None else env_float("NEMO_AUTOSCALE_COOLDOWN_S", 60.0)
        )
        self._rec = 0
        self._pending_sig = 0
        self._pending_n = 0
        self._last_flip: float | None = None
        self._prev_shed: dict[str, float] = {}
        self._doc: dict = {"recommendation": 0, "reason": "no data"}

    # ------------------------------------------------------------------ feed

    @staticmethod
    def _shed_total(snap: dict) -> float:
        return float(snap.get("counters", {}).get("serve.rejected", 0.0))

    def update(
        self,
        snaps: dict[str, dict],
        up: dict[str, bool],
        now: float | None = None,
    ) -> int:
        """One poll round: per-backend snapshots + liveness -> recommendation.
        Returns the (possibly unchanged) recommendation."""
        if now is None:
            now = time.monotonic()
        live = [t for t, ok in up.items() if ok]
        depth = inflight = capacity = 0.0
        for t in live:
            g = (snaps.get(t) or {}).get("gauges", {})
            depth += float(g.get("serve.queue_depth", 0.0))
            inflight += float(g.get("serve.inflight", 0.0))
            capacity += float(g.get("serve.capacity", DEFAULT_CAPACITY))
        util = (depth + inflight) / capacity if capacity else 0.0
        shed_delta = 0.0
        for t, snap in snaps.items():
            total = self._shed_total(snap or {})
            prev = self._prev_shed.get(t)
            if prev is not None and total > prev:
                shed_delta += total - prev
            self._prev_shed[t] = total

        if not live:
            sig, reason = 1, "no live replicas"
        elif shed_delta > 0:
            sig, reason = 1, f"shedding ({shed_delta:g} rejects since last poll)"
        elif util > self.up_util:
            sig, reason = 1, f"utilization {util:.2f} > {self.up_util:g}"
        elif util < self.down_util:
            sig, reason = -1, f"utilization {util:.2f} < {self.down_util:g}"
        else:
            sig, reason = 0, f"utilization {util:.2f} in band"

        if sig == self._rec:
            self._pending_n = 0
        else:
            if sig == self._pending_sig:
                self._pending_n += 1
            else:
                self._pending_sig, self._pending_n = sig, 1
            hold = self.hold_up if sig > self._rec else self.hold_down
            cooled = (
                sig > self._rec  # scaling up never waits out the cooldown
                or self._last_flip is None
                or now - self._last_flip >= self.cooldown_s
            )
            if self._pending_n >= hold and cooled:
                self._rec = sig
                self._pending_n = 0
                self._last_flip = now
            else:
                reason += f" (held: {self._pending_n}/{hold}" + (
                    "" if cooled else ", cooling down"
                ) + ")"

        self._doc = {
            "recommendation": self._rec,
            "desired_replicas": max(1, len(live) + self._rec),
            "replicas_live": len(live),
            "replicas_total": len(up),
            "utilization": round(util, 4),
            "queue_depth": depth,
            "inflight": inflight,
            "capacity": capacity,
            "shed_delta": shed_delta,
            "reason": reason,
            "thresholds": {
                "up_util": self.up_util,
                "down_util": self.down_util,
                "hold_up": self.hold_up,
                "hold_down": self.hold_down,
                "cooldown_s": self.cooldown_s,
            },
        }
        return self._rec

    # ------------------------------------------------------------------ read

    @property
    def recommendation(self) -> int:
        return self._rec

    def doc(self) -> dict:
        """The `/autoscale` JSON body (last computed round)."""
        return dict(self._doc)
