"""Admission control for the multi-tenant serving tier (ISSUE 8).

The sidecar serves many concurrent sessions over one accelerator, so the
first thing between a request and the device is a policy, not a mutex:

  * **bounded concurrency** — at most ``max_inflight`` requests execute at
    once (``--max-inflight`` / ``NEMO_SERVE_INFLIGHT``); the device
    serializes dispatches anyway, and everything past a small in-flight
    window only adds memory pressure and tail latency;
  * **bounded queueing** — at most ``max_queue`` requests wait
    (``--max-queue`` / ``NEMO_SERVE_QUEUE``).  Past that the request is
    REJECTED with a retry-after estimate instead of queued into a timeout:
    load shedding at admission is the difference between a slow server and
    a wedged one;
  * **per-tenant fairness** — queued tickets are granted round-robin
    ACROSS tenants (the ``nemo-tenant`` request metadata), so a greedy
    tenant's burst of N requests cannot starve another tenant's single
    one: the burst waits its turns, the singleton rides the next rotation;
  * **graceful drain** — ``begin_drain()`` refuses new admissions (the
    sidecar's SIGTERM handler flips ``/healthz`` to NOT_SERVING through
    this flag) while granted requests finish, bounded by
    ``NEMO_SERVE_DRAIN_S``.

Everything is observable on the PR-4 Prometheus surface:
``serve.queue_depth`` / ``serve.inflight`` gauges, ``serve.admitted`` /
``serve.rejected.<reason>`` counters plus per-tenant
``serve.tenant.<t>.requests|rejected|coalesced`` (bounded by the registry's
``NEMO_METRICS_MAX_SERIES`` cardinality cap — an adversarial tenant string
cannot mint unbounded series), and the queued-vs-executing latency split as
two histograms: ``serve.queued_s`` (admission wait) and ``serve.exec_s``
(slot-held execution).

Per-tenant SLO accounting (ISSUE 17) rides the same tier, because admission
is the ONE chokepoint every request crosses in both directions: end-to-end
latency (enqueue -> release, i.e. queued + executed — what the client felt)
lands in ``serve.slo.<t>.latency_s``, a ms-ladder histogram registered via
``set_buckets`` so SLO math gets finer bins than the default decade ladder;
sheds are charged against an error budget — the fraction of a tenant's
requests that may be rejected (``NEMO_SLO_SHED_BUDGET``, default 1%) —
surfaced as the ``serve.slo.<t>.budget_remaining`` gauge (1.0 = untouched,
0.0 = exhausted) with ``serve.slo.<t>.breaches`` counting each exhaustion
transition.  ``slo_snapshot()`` renders the whole table (per-tenant
request/shed totals, budget state, latency mean/max and p50/p95/p99 read
back off the histogram buckets) for telemetry.json and the Health surface.
Every shed also feeds the flight recorder's burst detector
(``obs.flight.note_shed``) so a shed *burst* dumps a postmortem bundle.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque

from nemo_tpu import obs

_log = obs.log.get_logger("nemo.serve")

#: Tenant strings ride metric names; anything outside this set becomes '_'
#: and the name is truncated so one tenant = one bounded family of series.
_TENANT_SAFE = re.compile(r"[^A-Za-z0-9_.-]")

DEFAULT_TENANT = "anon"


def sanitize_tenant(tenant: str | None) -> str:
    if not tenant:
        return DEFAULT_TENANT
    return _TENANT_SAFE.sub("_", str(tenant))[:32] or DEFAULT_TENANT


# Serving knobs follow the warn-and-default policy (the NEMO_PACK_XFER
# precedent): a typo'd env on a long-lived sidecar must degrade to the
# measured default, never crash-loop every admission.  The parsers now
# live in nemo_tpu/utils/env.py (ISSUE 9 satellite) — ONE home for the
# loud-vs-quiet policy; these aliases keep the serve-layer call sites.
from nemo_tpu.utils.env import env_float as _env_float  # noqa: E402
from nemo_tpu.utils.env import env_int as _env_int  # noqa: E402


def max_inflight_default() -> int:
    return _env_int("NEMO_SERVE_INFLIGHT", 4) or 1


def max_queue_default() -> int:
    return _env_int("NEMO_SERVE_QUEUE", 64)


def drain_seconds() -> float:
    """How long a SIGTERM'd sidecar waits for in-flight work
    (``NEMO_SERVE_DRAIN_S``, default 30 s)."""
    return _env_float("NEMO_SERVE_DRAIN_S", 30.0)


def stream_workers_default() -> int:
    """Per-AnalyzeDirStream-request concurrency (``NEMO_SERVE_STREAM_WORKERS``,
    default 2): how many of one stream's directories may hold admission
    tickets at once."""
    return max(1, _env_int("NEMO_SERVE_STREAM_WORKERS", 2))


def queue_timeout_seconds() -> float:
    """Upper bound on one ticket's admission wait (``NEMO_SERVE_QUEUE_S``,
    default 120 s): a queue that cannot drain within this is overload the
    client should hear about as a reject, not a hung RPC."""
    return _env_float("NEMO_SERVE_QUEUE_S", 120.0)


def slo_shed_budget() -> float:
    """Fraction of a tenant's requests that may be shed before its error
    budget reads exhausted (``NEMO_SLO_SHED_BUDGET``, default 0.01 = 1%)."""
    return _env_float("NEMO_SLO_SHED_BUDGET", 0.01)


#: Bucket ladder for ``serve.slo.<t>.latency_s`` — finer than the default
#: registry ladder at the ms..s range where serving SLOs live, coarser past
#: a minute (anything up there is already an outage, not a distribution).
SLO_LATENCY_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)


class AdmissionRejected(Exception):
    """Raised by :meth:`AdmissionController.enqueue` when the request
    cannot even wait.  ``reason`` is ``queue_full`` or ``draining``;
    ``retry_after_s`` is the controller's load-derived backoff estimate."""

    def __init__(self, reason: str, retry_after_s: float) -> None:
        super().__init__(
            f"admission rejected ({reason}); retry after ~{retry_after_s:.1f}s"
        )
        self.reason = reason
        self.retry_after_s = retry_after_s


class Ticket:
    """One request's admission state.  ``wait()`` until granted, then
    ``release()`` exactly once when the work (or the hand-off to a
    coalesced flight) is done; ``cancel()`` abandons a still-queued ticket
    (client disconnect, queue timeout)."""

    __slots__ = ("tenant", "_ctl", "_granted", "enqueued_at", "granted_at", "_done")

    def __init__(self, ctl: "AdmissionController", tenant: str) -> None:
        self.tenant = tenant
        self._ctl = ctl
        self._granted = threading.Event()
        self.enqueued_at = time.monotonic()
        self.granted_at: float | None = None
        self._done = False

    def wait(self, timeout: float | None = None) -> bool:
        """True once the ticket holds an execution slot."""
        return self._granted.wait(timeout)

    def position(self) -> int:
        """0 when granted, else 1-based position in the grant order (the
        round-robin projection — what a queued streaming client is told)."""
        return self._ctl._position(self)

    def release(self) -> None:
        self._ctl._release(self)

    def cancel(self) -> None:
        self._ctl._cancel(self)


class AdmissionController:
    """Bounded, tenant-fair admission queue in front of the execution path.

    Grants are round-robin across tenants with waiting tickets; within one
    tenant, FIFO.  All state changes happen under one lock; grant events
    wake the winning ticket's waiter.  The controller never runs work —
    handlers hold a granted ticket for the duration of their execution and
    release it in a ``finally``.
    """

    def __init__(
        self, max_inflight: int | None = None, max_queue: int | None = None
    ) -> None:
        self.max_inflight = max_inflight if max_inflight is not None else max_inflight_default()
        self.max_queue = max_queue if max_queue is not None else max_queue_default()
        self._lock = threading.Lock()
        self._queues: dict[str, deque[Ticket]] = {}
        self._rr: deque[str] = deque()  # tenant rotation (head = next up)
        self._queued = 0
        self._inflight = 0
        self._streams = 0
        self._draining = False
        #: EWMA of executed-slot seconds — the retry-after estimator's view
        #: of how fast one slot turns over.
        self._exec_ewma = 0.5
        #: tenant -> [requests, sheds, budget_breached] — the SLO ledger.
        #: Bounded by the same force that bounds per-tenant metric series:
        #: tenants are sanitized 32-char strings and the registry cap stops
        #: minting anyway, so a dict here cannot outgrow the metric space.
        self._slo: dict[str, list] = {}
        #: tenants whose latency ladder is already registered (set_buckets
        #: is idempotent but takes the registry lock; skip after first).
        self._slo_ladders: set[str] = set()

    # ------------------------------------------------------------- state

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def queued(self) -> int:
        return self._queued

    @property
    def streams(self) -> int:
        return self._streams

    # ------------------------------------------------------ stream presence

    def begin_stream(self) -> None:
        """Register one live server-streaming RPC (AnalyzeDirStream).  The
        stream handler holds no admission ticket itself — its per-directory
        workers do — so without this presence a SIGTERM drain could see
        inflight==0 between a worker's release and the handler's terminal
        ``done`` event and stop the server mid-stream, severing the stream
        instead of finishing it (ISSUE 9 satellite).  Streams are admitted
        even while draining ONLY in the sense that an already-started one
        finishes; new per-directory tickets still reject."""
        with self._lock:
            self._streams += 1
        obs.metrics.gauge("serve.streams", self._streams)

    def end_stream(self) -> None:
        with self._lock:
            self._streams = max(0, self._streams - 1)
        obs.metrics.gauge("serve.streams", self._streams)

    def retry_after_s(self) -> float:
        """Load-derived backoff hint: the queue's worth of slot turnovers
        ahead of a would-be new arrival, clamped to a sane window."""
        with self._lock:
            pending = self._queued + self._inflight
            est = (pending + 1) / max(self.max_inflight, 1) * max(self._exec_ewma, 0.1)
        return min(max(est, 0.5), 60.0)

    # ----------------------------------------------------------- enqueue

    def enqueue(self, tenant: str | None = None) -> Ticket:
        """Admit-or-reject.  Returns a ticket (possibly already granted);
        raises :class:`AdmissionRejected` when draining or the queue is
        full."""
        tenant = sanitize_tenant(tenant)
        obs.metrics.inc("serve.requests")
        obs.metrics.inc(f"serve.tenant.{tenant}.requests")
        with self._lock:
            self._slo.setdefault(tenant, [0, 0, False])[0] += 1
            if self._draining:
                reason = "draining"
            elif self._queued >= self.max_queue and self._inflight >= self.max_inflight:
                reason = "queue_full"
            else:
                t = Ticket(self, tenant)
                q = self._queues.get(tenant)
                if q is None:
                    q = self._queues[tenant] = deque()
                    self._rr.append(tenant)
                q.append(t)
                self._queued += 1
                self._grant_locked()
                self._gauges_locked()
                return t
        obs.metrics.inc("serve.rejected")
        obs.metrics.inc(f"serve.rejected.{reason}")
        obs.metrics.inc(f"serve.tenant.{tenant}.rejected")
        self.record_shed(tenant, reason)
        retry = self.retry_after_s() if reason == "queue_full" else 1.0
        _log.warning(
            "serve.rejected", tenant=tenant, reason=reason,
            retry_after_s=round(retry, 2),
        )
        raise AdmissionRejected(reason, retry)

    # ----------------------------------------------------- SLO accounting

    def record_shed(self, tenant: str, reason: str) -> None:
        """Charge one shed against `tenant`'s error budget and feed the
        flight recorder's burst detector.  Called from the enqueue reject
        path AND from the server's queue-timeout reject (a timeout is a shed
        the queue took too long to admit — the client experienced the same
        refusal), so the budget sees every refused request regardless of
        which tier refused it."""
        tenant = sanitize_tenant(tenant)
        budget = slo_shed_budget()
        with self._lock:
            rec = self._slo.setdefault(tenant, [0, 0, False])
            rec[1] += 1
            requests, sheds, breached = max(rec[0], 1), rec[1], rec[2]
            remaining = max(0.0, 1.0 - (sheds / requests) / budget) if budget > 0 else 0.0
            now_breached = remaining <= 0.0
            rec[2] = now_breached
        obs.metrics.gauge(f"serve.slo.{tenant}.budget_remaining", remaining)
        if now_breached and not breached:
            obs.metrics.inc(f"serve.slo.{tenant}.breaches")
            _log.warning(
                "serve.slo_breach", tenant=tenant, requests=requests,
                sheds=sheds, shed_budget=budget,
            )
        obs.flight.note_shed(reason, tenant)

    def _slo_observe_locked(self, ticket: Ticket, now: float) -> None:
        """End-to-end latency (enqueue -> release: queued + executed — the
        wall the client saw) into the tenant's ms-ladder SLO histogram.
        Caller holds the lock (the registry has its own and never re-enters
        admission, so the nesting is one-directional and safe)."""
        tenant = ticket.tenant
        name = f"serve.slo.{tenant}.latency_s"
        if tenant not in self._slo_ladders:
            obs.metrics.set_buckets(name, SLO_LATENCY_BUCKETS)  # metrics-doc: serve.slo.<tenant>.latency_s
            self._slo_ladders.add(tenant)
        obs.metrics.observe(name, now - ticket.enqueued_at)  # metrics-doc: serve.slo.<tenant>.latency_s

    # ------------------------------------------------------- grant logic

    def _grant_locked(self) -> None:
        """Hand free slots to queued tickets, one tenant per rotation
        step.  Caller holds the lock."""
        while self._inflight < self.max_inflight and self._queued > 0:
            # Rotate to the next tenant with a waiter; drop empty queues
            # from the rotation as they surface.
            for _ in range(len(self._rr)):
                tenant = self._rr[0]
                q = self._queues.get(tenant)
                if q:
                    break
                self._rr.popleft()
                self._queues.pop(tenant, None)
            else:
                return  # rotation empty (stale counters cannot happen: _queued > 0 implies a waiter)
            self._rr.rotate(-1)
            t = q.popleft()
            self._queued -= 1
            self._inflight += 1
            t.granted_at = time.monotonic()
            obs.metrics.inc("serve.admitted")
            obs.metrics.observe("serve.queued_s", t.granted_at - t.enqueued_at)
            t._granted.set()

    def _gauges_locked(self) -> None:
        obs.metrics.gauge("serve.queue_depth", self._queued)
        obs.metrics.gauge("serve.inflight", self._inflight)

    def _position(self, ticket: Ticket) -> int:
        with self._lock:
            if ticket._granted.is_set():
                return 0
            # Project the round-robin grant order: tenants are served one
            # ticket per rotation, so ticket k (0-based) of its tenant's
            # queue goes out in rotation k.
            q = self._queues.get(ticket.tenant)
            if q is None or ticket not in q:
                return 0
            k = list(q).index(ticket)
            ahead = k  # own tenant's earlier tickets
            for tenant, other in self._queues.items():
                if tenant != ticket.tenant:
                    ahead += min(len(other), k + 1)
            return ahead + 1

    def _release(self, ticket: Ticket) -> None:
        with self._lock:
            if ticket._done:
                return
            ticket._done = True
            if not ticket._granted.is_set():
                # Released while still queued (cancel path alias).
                self._remove_queued_locked(ticket)
                self._gauges_locked()
                return
            self._inflight -= 1
            if ticket.granted_at is not None:
                now = time.monotonic()
                held = now - ticket.granted_at
                obs.metrics.observe("serve.exec_s", held)
                self._exec_ewma = 0.7 * self._exec_ewma + 0.3 * held
                self._slo_observe_locked(ticket, now)
            self._grant_locked()
            self._gauges_locked()

    def _cancel(self, ticket: Ticket) -> None:
        self._release(ticket)

    def _remove_queued_locked(self, ticket: Ticket) -> None:
        q = self._queues.get(ticket.tenant)
        if q is not None:
            try:
                q.remove(ticket)
                self._queued -= 1
            except ValueError:
                pass

    # -------------------------------------------------------------- drain

    def begin_drain(self) -> None:
        """Refuse all new admissions from now on (idempotent).  In-flight
        and already-queued work still completes — drain is about new
        arrivals, not abandoning accepted ones."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        obs.metrics.inc("serve.drain_begun")
        _log.info("serve.draining", inflight=self._inflight, queued=self._queued)

    def drain_wait(self, timeout_s: float) -> bool:
        """Wait until nothing is in flight, queued, OR mid-stream; True
        when drained.  Streams count (ISSUE 9): an AnalyzeDirStream must
        emit its terminal ``done`` event before the server stops — a
        drained-by-tickets-only wait could sever it between its last
        worker's release and that final yield."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                if self._inflight == 0 and self._queued == 0 and self._streams == 0:
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)


# --------------------------------------------------------------- singleton

_controller: AdmissionController | None = None
_controller_lock = threading.Lock()


def controller() -> AdmissionController:
    """The process-wide admission controller (created on first use from the
    env knobs — the sidecar's ``main()`` writes its CLI flags into the env
    before the first access, the corpus-cache precedent)."""
    global _controller
    with _controller_lock:
        if _controller is None:
            _controller = AdmissionController()
        return _controller


def reset_controller() -> None:
    """Drop the singleton so the next access re-reads the env (tests)."""
    global _controller
    with _controller_lock:
        _controller = None


# ------------------------------------------------------------- SLO table


def _hist_quantile(hist: dict, q: float) -> float:
    """Quantile estimate off a snapshot histogram: the smallest bucket
    upper bound covering q of the observations (standard Prometheus
    histogram_quantile coarseness — exact would need raw samples).
    Observations past the ladder's top land in +Inf; report the lifetime
    max for those rather than infinity."""
    count = hist.get("count", 0)
    if not count:
        return 0.0
    need = q * count
    for le, cum in hist.get("buckets", []):
        if cum >= need:
            return float(le)
    return float(hist.get("max", 0.0))


def slo_snapshot() -> dict:
    """The per-tenant SLO table: request/shed totals, error-budget state,
    and latency stats (mean/max plus p50/p95/p99 read back off the SLO
    histogram's buckets).  Empty dict when no serving traffic has run —
    telemetry.json and the report hide the section then.  Reads the live
    singleton WITHOUT creating it: a CLI run that never served must not
    boot an admission controller just to report that it didn't."""
    with _controller_lock:
        ctl = _controller
    if ctl is None:
        return {}
    with ctl._lock:
        ledger = {t: list(rec) for t, rec in ctl._slo.items()}
    if not ledger:
        return {}
    budget = slo_shed_budget()
    hists = obs.metrics.snapshot()["histograms"]
    table: dict = {}
    for tenant in sorted(ledger):
        requests, sheds, breached = ledger[tenant]
        ratio = sheds / max(requests, 1)
        row = {
            "requests": int(requests),
            "sheds": int(sheds),
            "shed_ratio": round(ratio, 6),
            "shed_budget": budget,
            "budget_remaining": round(
                max(0.0, 1.0 - ratio / budget) if budget > 0 else 0.0, 6
            ),
            "breached": bool(breached),
        }
        h = hists.get(f"serve.slo.{tenant}.latency_s")
        if h:
            row["latency"] = {
                "count": h["count"],
                "mean_s": round(h["mean"], 6),
                "max_s": round(h["max"], 6),
                "p50_s": _hist_quantile(h, 0.50),
                "p95_s": _hist_quantile(h, 0.95),
                "p99_s": _hist_quantile(h, 0.99),
            }
        table[tenant] = row
    return table
