"""Cross-request continuous batching for kernel dispatches (ISSUE 8).

LLM inference servers fill each device step with rows from DIFFERENT
requests; the analog here is the sidecar's Kernel RPC: several in-flight
client sessions dispatching the same verb with the same joint-bucket
signature (identical statics, identical per-row array shapes/dtypes — the
jit cache key) are one padded device launch, not N.

Only the row-independent verbs merge — ``condition``, ``simplify``,
``proto`` — and only their RUN-BATCHED dispatch shape: each output row is
a pure function of its input row (the sparse/dense parity suites pin
that), so concatenating requests along the run axis and slicing the
outputs back apart is exact.  The same verbs also dispatch PER-GRAPH
(``is_goal`` a 1-D node vector, ``adj`` a 2-D matrix — the stable
single-verb kernel API), where the leading axis is nodes, not runs;
:data:`BATCH_RANK` gates on the canonical array's rank so a per-graph
dispatch is never merged (two unrelated graphs concatenated along the
node axis would corrupt both).  ``fused``/``giant`` never merge: the
fused step diffs every row against its batch's row 0 (the corpus
baseline) and reduces prototypes across the whole batch, so rows from
different corpora in one batch would change results.  ``diff`` reads the
good-run adjacency from its arrays — merging would require content-equal
good graphs, which the signature cannot see.

Mechanics — continuous, not windowed: the first arrival for a signature
launches immediately (idle servers add zero latency); arrivals while a
launch is in flight accumulate and go out as ONE merged launch the moment
the device frees (an optional ``NEMO_SERVE_BATCH_WINDOW_MS`` adds a short
gather wait for bursty-but-not-overlapping clients, default 0).  Each
leader runs exactly ONE launch — its own request plus whatever
accumulated — then HANDS LEADERSHIP to the first still-waiting request
(promotion), so a sustained arrival stream advances launch by launch with
every request's latency bounded by its own batch, a failed launch fails
only the requests IN that batch, and the in-flight token can never be
held by a thread whose own work already finished.  The merged batch pads
its run axis to the bucket power-of-two (``graphs/packed.py:bucket_size``)
so the jit signature stays stable across merge sizes, and executes as a
device-pinned ``parallel/sched.py:Job`` through the heterogeneous
scheduler — same decision records, metrics, and cost-model feedback as
the pipeline's own buckets, tagged ``source="serve"``.  The executor's
``rows`` hint carries the REAL merged row count so the PR-4 cost table
scales by rows_frac and pad rows never count (the PR-7 contract);
per-request row attribution lands in ``serve.batch.request_rows``.

Demux: each request's rows are a contiguous [offset, offset+rows) slice of
the merged batch; every output's leading dim is verified against the
padded width before slicing, so a non-per-row output can never be
mis-attributed — it fails loudly instead.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from nemo_tpu import obs
from nemo_tpu.serve.admission import _env_float

_log = obs.log.get_logger("nemo.serve")

#: Merge-eligible verbs mapped to (canonical array, required rank) of their
#: RUN-BATCHED dispatch shape.  A dispatch whose canonical array has any
#: other rank is the per-graph form of the same verb (leading axis = nodes)
#: and must never be merged.
BATCH_RANK = {
    "condition": ("is_goal", 2),
    "simplify": ("is_goal", 2),
    "proto": ("adj", 3),
    # The synthesis verb (ISSUE 13) is row-independent by construction —
    # every output row is a function of its own run's planes — and only
    # ever dispatches run-batched ([B,V] is_goal), so cross-request
    # merging is exact.
    "synth_ext": ("is_goal", 2),
}

#: Verbs whose run-batched outputs are all per-row functions of per-row
#: inputs (see module docstring for why fused/giant/diff are excluded).
#: The sparse-CSR device verbs (ISSUE 10: "sparse_fused", "sparse_diff")
#: are excluded for the fused/diff reasons exactly — sparse_fused carries
#: the cross-run prototype reductions, sparse_diff diffs every row against
#: one shared good graph — so they pass through solo like their dense
#: twins.
BATCHABLE_VERBS = frozenset(BATCH_RANK)


def window_seconds() -> float:
    return _env_float("NEMO_SERVE_BATCH_WINDOW_MS", 0.0) / 1000.0


def dispatch_signature(verb: str, arrays: dict, params: dict):
    """The merge-compatibility key: verb + every static param + every
    array's (name, dtype, trailing shape).  Exactly what the jit cache
    keys on minus the leading (run) dim — two dispatches sharing this
    signature concatenate into one program's batch."""
    p = tuple(sorted((k, int(v)) for k, v in params.items()))
    a = tuple(
        sorted(
            (n, str(np.asarray(x).dtype), tuple(np.shape(x)[1:]))
            for n, x in arrays.items()
            if x is not None
        )
    )
    return (verb, p, a)


def _eligible_rows(verb: str, arrays: dict) -> int | None:
    """The run-batch width of a merge-eligible dispatch, or None.

    Eligibility gates on the canonical array's RANK (run-batched vs
    per-graph dispatch of the same verb) and on every array sharing one
    leading dim — anything else executes solo."""
    spec = BATCH_RANK.get(verb)
    if spec is None:
        return None
    name, rank = spec
    canon = arrays.get(name)
    if canon is None or np.ndim(canon) != rank:
        return None
    dims = {
        int(np.shape(a)[0])
        for a in arrays.values()
        if a is not None and np.ndim(a) > 0
    }
    return dims.pop() if len(dims) == 1 else None


class _Pending:
    __slots__ = ("arrays", "rows", "event", "result", "error", "promoted")

    def __init__(self, arrays: dict, rows: int) -> None:
        self.arrays = arrays
        self.rows = rows
        self.event = threading.Event()
        self.result: dict | None = None
        self.error: BaseException | None = None
        #: Set (with the event) when leadership is handed to this waiter
        #: instead of a result: it wakes, drains the queue, and launches.
        self.promoted = False


class _Group:
    __slots__ = ("in_flight", "pending")

    def __init__(self) -> None:
        self.in_flight = False
        self.pending: list[_Pending] = []


class KernelBatcher:
    """Per-signature continuous batcher over an executor's ``run``."""

    #: Bound on one waiter's wait for its merged launch.
    WAIT_TIMEOUT_S = 600.0

    def __init__(self, window_s: float | None = None) -> None:
        self.window_s = window_seconds() if window_s is None else float(window_s)
        self._lock = threading.Lock()
        self._groups: dict[tuple, _Group] = {}

    # ------------------------------------------------------------ public

    def run(
        self, executor, verb: str, arrays: dict, params: dict, rows: int | None = None
    ) -> dict[str, np.ndarray]:
        """Drop-in for ``executor.run``: merge-eligible dispatches ride the
        continuous batch; everything else (non-batchable verbs, per-graph
        dispatch shapes) executes directly, counted ``serve.batch.solo``."""
        my_rows = _eligible_rows(verb, arrays)
        if not my_rows:
            obs.metrics.inc("serve.batch.solo")
            return executor.run(verb, arrays, params, rows=rows)
        sig = dispatch_signature(verb, arrays, params)
        me = _Pending(arrays, my_rows)
        with self._lock:
            group = self._groups.get(sig)
            if group is None:
                group = self._groups[sig] = _Group()
            if group.in_flight:
                # A launch for this signature is on the device: accumulate.
                group.pending.append(me)
                leader = False
            else:
                group.in_flight = True
                leader = True
        if not leader:
            if not me.event.wait(self.WAIT_TIMEOUT_S):
                with self._lock:
                    if me in group.pending:
                        group.pending.remove(me)
                        raise TimeoutError(
                            f"batched {verb} dispatch not launched in "
                            f"{self.WAIT_TIMEOUT_S:.0f}s"
                        )
                # Raced a launch/promotion that already took this entry out
                # of the queue: the event is moments away.
                me.event.wait(self.WAIT_TIMEOUT_S)
            if me.error is not None:
                raise me.error
            if me.result is not None:
                return me.result
            if not me.promoted:  # double timeout with no handoff
                raise TimeoutError(
                    f"batched {verb} dispatch neither launched nor promoted in "
                    f"{2 * self.WAIT_TIMEOUT_S:.0f}s"
                )
            # Leadership handoff: fall through and launch.
        # Leader for exactly ONE launch: this request plus everything
        # pending right now.  Afterwards the token is handed to the first
        # still-waiting request (promotion) or released — a leader never
        # drains other requests' batches after its own work finished, so
        # its latency is bounded and a later batch's failure cannot reach
        # it.
        if self.window_s:
            time.sleep(self.window_s)
        with self._lock:
            batch = [me] + group.pending
            group.pending = []
        try:
            self._launch(executor, verb, params, batch, sig)
        finally:
            self._handoff(group, sig)
        if me.error is not None:
            raise me.error
        assert me.result is not None
        return me.result

    def _handoff(self, group: _Group, sig: tuple) -> None:
        """Pass the in-flight token to the next waiter, or retire it.  The
        idle group is dropped from the table — signatures arrive verbatim
        from clients (shapes, statics), so a retained entry per distinct
        signature would grow without bound on a long-lived sidecar."""
        with self._lock:
            if group.pending:
                nxt = group.pending.pop(0)
                nxt.promoted = True
                nxt.event.set()  # token transfers; in_flight stays True
            else:
                group.in_flight = False
                if self._groups.get(sig) is group:
                    del self._groups[sig]

    # ----------------------------------------------------------- launch

    def _launch(
        self, executor, verb: str, params: dict, batch: list[_Pending], sig: tuple
    ) -> None:
        from nemo_tpu.graphs.packed import bucket_size
        from nemo_tpu.parallel import sched

        total = sum(p.rows for p in batch)
        padded = bucket_size(total, minimum=1)
        names = list(batch[0].arrays)
        try:
            merged: dict = {}
            for n in names:
                parts = [np.asarray(p.arrays[n]) for p in batch]
                if padded > total:
                    # Pad rows are copies of the first request's row 0 —
                    # per-row verbs compute them independently and the
                    # demux below never returns them.
                    parts.append(
                        np.repeat(parts[0][:1], padded - total, axis=0)
                    )
                merged[n] = np.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]

            v = int(params.get("v", params.get("num_tables", 0)))
            e = int(np.shape(merged.get("edge_src", ()))[-1]) if "edge_src" in merged else 0

            def execute(lane: str, reason: str, stolen: bool) -> dict:
                # The rows hint carries the REAL merged row count past the
                # pad (backend/jax_backend.py scales the cost accounting
                # by rows_frac).
                res = executor.run(verb, merged, params, rows=total)
                # A compiled launch's wall must not feed the scheduler's
                # warm-execution EWMA (the Job.wall_tainted contract).
                if getattr(executor, "last_dispatch_compiled", False):
                    job.wall_tainted = True
                return res

            job = sched.Job(
                index=0,
                verb=verb,
                rows=total,
                v=v,
                e=e,
                work=total * max(v + e, 1),
                execute=execute,
                pinned="device",
                reason="serve_batch",
                source="serve",
            )
            out = sched.HeterogeneousScheduler().run([job])[0]

            obs.metrics.inc("serve.batch.launches")
            obs.metrics.inc("serve.batch.merged_requests", len(batch))
            if len(batch) > 1:
                obs.metrics.inc("serve.batch.coalesced_requests", len(batch) - 1)
            obs.metrics.inc("serve.batch.rows", total)
            obs.metrics.inc("serve.batch.pad_rows", padded - total)
            for n, o in out.items():
                lead = int(np.shape(o)[0]) if np.ndim(o) > 0 else -1
                if lead != padded:
                    raise RuntimeError(
                        f"kernel {verb!r} output {n!r} is not per-row shaped "
                        f"(leading dim {lead}, batch {padded}); it cannot be "
                        "demuxed across requests — remove the verb from "
                        "serve.batch.BATCHABLE_VERBS"
                    )
            off = 0
            for p in batch:
                obs.metrics.observe("serve.batch.request_rows", p.rows)
                p.result = {n: np.asarray(o)[off : off + p.rows] for n, o in out.items()}
                off += p.rows
        except BaseException as ex:
            # Only THIS batch's requests fail; the handoff in run()'s
            # finally passes the token on regardless.
            for p in batch:
                p.error = ex
            raise
        finally:
            for p in batch:
                p.event.set()


# --------------------------------------------------------------- singleton

_batcher: KernelBatcher | None = None
_batcher_lock = threading.Lock()


def batcher() -> KernelBatcher:
    global _batcher
    with _batcher_lock:
        if _batcher is None:
            _batcher = KernelBatcher()
        return _batcher


def reset_batcher() -> None:
    global _batcher
    with _batcher_lock:
        _batcher = None
