"""Single-flight request coalescing keyed on content addresses (ISSUE 8).

Concurrent ``AnalyzeDir`` requests whose corpora have the same content
address — store segment fingerprints + statics + wire/ABI versions, the
exact tier-3 key ``analysis/delta.py:blob_cache_key`` mints for the result
cache — are the SAME computation, so only one should run: the first
arrival becomes the flight's **leader** and executes; every concurrent
duplicate attaches as a **subscriber** and receives the leader's
byte-identical serialized response.  This is what makes a thundering herd
of identical sessions (a dashboard refresh fan-out, a CI matrix over one
corpus) cost one ANALYSIS instead of N.  Scope: the dedup covers the
device dispatch + response serialization — each request still ingests its
directory first (the content key IS the store's segment fingerprints),
which on a warm corpus store is a milliseconds mmap; a fully cold herd
pays N parses (only the store populate is serialized, at its writer lock)
before the first key exists to coalesce on.

By default only IN-FLIGHT work coalesces: the moment a flight completes it
leaves the table, and a later identical request belongs to the result
cache (store/rcache.py), the durable dedup tier — keeping the two tiers'
counters and trailing-metadata statuses disjoint (a repeat is an
``rcache: hit``, never a phantom ``coalesce: hit``).
``NEMO_SERVE_COALESCE_LINGER_S`` (default 0) keeps completed flights
joinable for a window so near-concurrent stragglers — admitted a beat
after the leader finished, e.g. queued behind the in-flight cap with the
result cache off — still coalesce.  A lingering payload can never be
stale: the key is a pure content address, so the bytes are what a fresh
execution would produce.

The caller (service/server.py) counts ``serve.coalesce.leader`` /
``serve.coalesce.hit`` and releases its admission slot before waiting as a
subscriber — a subscriber consumes no execution capacity, only patience.
"""

from __future__ import annotations

import threading
import time

from nemo_tpu import obs
from nemo_tpu.serve.admission import _env_float

_log = obs.log.get_logger("nemo.serve")


def linger_seconds() -> float:
    return _env_float("NEMO_SERVE_COALESCE_LINGER_S", 0.0)


class Flight:
    """One in-flight (or lingering) keyed execution."""

    __slots__ = ("key", "event", "payload", "meta", "error", "done_at", "subscribers")

    def __init__(self, key: str) -> None:
        self.key = key
        self.event = threading.Event()
        self.payload: bytes | None = None
        self.meta: dict = {}
        self.error: BaseException | None = None
        self.done_at: float | None = None
        self.subscribers = 0

    #: Bound on one subscriber's wait for its leader (matches the client's
    #: default RPC deadline — a subscriber parked past the point every
    #: waiting client has given up is a leaked pool thread, not a service).
    WAIT_TIMEOUT_S = 300.0

    def wait_result(
        self, timeout: float | None = None, is_alive=None
    ) -> tuple[bytes, dict]:
        """Wait for the leader's payload.  ``is_alive`` (optional callable,
        e.g. a gRPC context's ``is_active``) is polled so a subscriber
        whose client disconnected frees its handler thread instead of
        parking it for the full window."""
        deadline = time.monotonic() + (self.WAIT_TIMEOUT_S if timeout is None else timeout)
        while not self.event.wait(0.5):
            if is_alive is not None and not is_alive():
                raise TimeoutError(
                    f"client went away waiting on coalesced flight {self.key[:12]}"
                )
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"coalesced flight {self.key[:12]} did not complete in time"
                )
        if self.error is not None:
            # Leader failures propagate to every subscriber: N identical
            # requests fail identically rather than N-1 retrying a
            # computation that just proved itself broken.
            raise self.error
        assert self.payload is not None
        return self.payload, dict(self.meta)


class SingleFlight:
    """Keyed single-flight table with a linger window for stragglers.

    Memory contract for the long-lived sidecar: completed flights hold a
    full serialized response, so the table is swept of expired entries on
    every join/complete AND hard-capped at :data:`MAX_LINGERING` completed
    flights (oldest-done evicted first; in-flight leaders are never
    evicted) — a burst of N distinct corpora followed by silence cannot
    pin N payloads forever."""

    #: Hard bound on COMPLETED flights retained for the linger window.
    MAX_LINGERING = 256

    def __init__(self, linger_s: float | None = None) -> None:
        self.linger_s = linger_seconds() if linger_s is None else float(linger_s)
        self._lock = threading.Lock()
        self._flights: dict[str, Flight] = {}

    def _sweep_locked(self, now: float) -> None:
        """Drop expired completed flights; cap the rest (caller holds the
        lock).  Subscribers already attached keep their Flight reference —
        eviction only forgets the key."""
        dead = [
            k
            for k, f in self._flights.items()
            if f.done_at is not None
            and (f.error is not None or now - f.done_at > self.linger_s)
        ]
        for k in dead:
            del self._flights[k]
        done = [f for f in self._flights.values() if f.done_at is not None]
        if len(done) > self.MAX_LINGERING:
            done.sort(key=lambda f: f.done_at)
            for f in done[: len(done) - self.MAX_LINGERING]:
                if self._flights.get(f.key) is f:
                    del self._flights[f.key]

    def join(self, key: str) -> tuple[str, Flight]:
        """("leader", fresh flight) for the first arrival, ("hit", flight)
        for a duplicate of an in-flight or lingering one.  A leader MUST
        call :meth:`complete` or :meth:`fail` exactly once."""
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            f = self._flights.get(key)
            if f is not None:
                f.subscribers += 1
                return "hit", f
            f = Flight(key)
            self._flights[key] = f
            return "leader", f

    def complete(self, flight: Flight, payload: bytes, meta: dict) -> None:
        with self._lock:
            flight.payload = payload
            flight.meta = dict(meta)
            flight.done_at = time.monotonic()
            self._sweep_locked(flight.done_at)
        flight.event.set()
        if self.linger_s == 0:
            self._evict(flight)

    def fail(self, flight: Flight, error: BaseException) -> None:
        """Failed flights never linger: the next identical request should
        retry the computation, not inherit a transient failure forever."""
        with self._lock:
            flight.error = error
            flight.done_at = time.monotonic()
        flight.event.set()
        self._evict(flight)

    def _evict(self, flight: Flight) -> None:
        with self._lock:
            if self._flights.get(flight.key) is flight:
                del self._flights[flight.key]

    def clear(self) -> None:
        """Forget every flight (tests; in-flight leaders still complete
        their own Flight objects — subscribers already attached keep their
        reference)."""
        with self._lock:
            self._flights.clear()


# --------------------------------------------------------------- singleton

_flights: SingleFlight | None = None
_flights_lock = threading.Lock()


def flights() -> SingleFlight:
    """The process-wide flight table: in-process servers share it (same
    content address -> same bytes, whoever's handler runs the flight)."""
    global _flights
    with _flights_lock:
        if _flights is None:
            _flights = SingleFlight()
        return _flights


def reset_flights() -> None:
    global _flights
    with _flights_lock:
        _flights = None
