"""Synthetic Molly-corpus generator.

Molly (the external Scala fault injector the reference consumes,
reference: README.md:5-8) is not available in this environment, so this module
fabricates Molly-format output directories — runs.json,
run_<i>_{pre,post}_provenance.json, run_<i>_spacetime.dot — with the exact JSON
schema of reference faultinjectors/data-types.go:6-98 and the file layout read
by faultinjectors/molly.go:18,59-60 and graphing/hazard-analysis.go:25.

The generated protocol is an asynchronous primary/backup replication in the
spirit of the reference case study (case-studies/pb_asynchronous.ded): a client
C sends a request to primary P, which acks immediately (antecedent `pre` =
payload acked) and replicates to backups in the background (consequent `post` =
payload logged on all correct replicas).  Fault-injection runs either:

  * succeed with full replication (kind "success");
  * lose a replicate message, violating the invariant (kind "fail");
  * lose the initial request, so the antecedent is never achieved and the
    invariant holds vacuously (kind "vacuous" — still status "success").

Provenance graphs are built with realistic structure: alternating
goal->rule->goal edges, @next persistence chains of varying length (these are
what graph simplification contracts), @async network rules, and clock goals.
"""

from __future__ import annotations

import json
import os
import random
from dataclasses import dataclass, field
from typing import Any


@dataclass
class ProvBuilder:
    """Accumulates one provenance graph in Molly JSON form."""

    goals: list[dict[str, Any]] = field(default_factory=list)
    rules: list[dict[str, Any]] = field(default_factory=list)
    edges: list[dict[str, Any]] = field(default_factory=list)
    _n: int = 0

    def goal(self, table: str, args: list[str], time: int | str = "") -> str:
        gid = f"goal_{self._n}"
        self._n += 1
        label = f"{table}({', '.join(str(a) for a in args)})"
        self.goals.append({"id": gid, "label": label, "table": table, "time": str(time)})
        return gid

    def clock_goal(self, frm: str, to: str, t: int, wildcard: bool = False) -> str:
        """Clock goals carry their time inside the label; the loader extracts it
        with the reference's regexes (faultinjectors/molly.go:76-89)."""
        last = "__WILDCARD__" if wildcard else str(t + 1)
        return self.goal("clock", [frm, to, str(t), last])

    def rule(self, table: str, rtype: str = "", label: str | None = None) -> str:
        rid = f"rule_{self._n}"
        self._n += 1
        self.rules.append(
            {"id": rid, "label": label if label is not None else table, "table": table, "type": rtype}
        )
        return rid

    def edge(self, src: str, dst: str) -> None:
        self.edges.append({"from": src, "to": dst})

    def next_chain(self, table: str, args: list[str], t_hi: int, t_lo: int) -> tuple[str, str]:
        """Build goal@t_hi -> next-rule -> goal@t_hi-1 -> ... -> goal@t_lo.

        Returns (top goal id, bottom goal id).  This is the @next
        timer/persistence chain shape that SimplifyProv contracts
        (reference: graphing/preprocessing.go:70-78).
        """
        top = self.goal(table, args, t_hi)
        cur = top
        for t in range(t_hi - 1, t_lo - 1, -1):
            r = self.rule(table, "next", label=f"{table}_next")
            g = self.goal(table, args, t)
            self.edge(cur, r)
            self.edge(r, g)
            cur = g
        return top, cur

    def build(self) -> dict[str, Any]:
        return {"goals": self.goals, "rules": self.rules, "edges": self.edges}


def _build_pre_prov(
    achieved: bool, eot: int, ack_time: int, client: str, primary: str, payload: str
) -> dict[str, Any]:
    """Antecedent provenance: pre(payload) <- acked(...) <- ack@async <- request@async."""
    b = ProvBuilder()
    if not achieved:
        # Antecedent never held: only the inert begin fact has provenance.
        g_begin = b.goal("begin", [client, payload], 1)
        r_begin = b.rule("begin")
        b.edge(g_begin, r_begin)
        g_clock = b.clock_goal(client, client, 1)
        b.edge(r_begin, g_clock)
        return b.build()

    g_pre = b.goal("pre", [payload], eot)
    r_pre = b.rule("pre")
    b.edge(g_pre, r_pre)

    # acked persistence chain from eot down to the ack time.
    g_acked_top, g_acked_bot = b.next_chain("acked", [client, primary, payload], eot, ack_time)
    b.edge(r_pre, g_acked_top)

    # acked(...) :- ack(...): deductive rule under the bottom of the chain.
    r_acked = b.rule("acked")
    b.edge(g_acked_bot, r_acked)
    g_ack = b.goal("ack", [client, primary, payload], ack_time)
    b.edge(r_acked, g_ack)

    # ack@async :- request: network hop primary -> client.
    r_ack = b.rule("ack", "async")
    b.edge(g_ack, r_ack)
    g_req = b.goal("request", [primary, payload, client], ack_time - 1)
    b.edge(r_ack, g_req)
    b.edge(r_ack, b.clock_goal(primary, client, ack_time - 1))

    # request@async :- begin, conn_out: network hop client -> primary.
    r_req = b.rule("request", "async")
    b.edge(g_req, r_req)
    b.edge(r_req, b.goal("begin", [client, payload], 1))
    b.edge(r_req, b.goal("conn_out", [client, primary], 1))
    b.edge(r_req, b.clock_goal(client, primary, 1))

    return b.build()


def _build_post_prov(
    replicas_logged: list[str],
    eot: int,
    log_time: int,
    achieved: bool,
    primary: str,
    client: str,
    payload: str,
) -> dict[str, Any]:
    """Consequent provenance: post(payload) <- log(Rep, payload) for each replica."""
    b = ProvBuilder()
    if achieved:
        g_post = b.goal("post", [payload], eot)
        r_post = b.rule("post")
        b.edge(g_post, r_post)

    g_req = None
    for rep in replicas_logged:
        g_log_top, g_log_bot = b.next_chain("log", [rep, payload], eot, log_time)
        if achieved:
            b.edge(r_post, g_log_top)

        # log(Rep, payload) :- replicate(Rep, payload, ...).
        r_log = b.rule("log")
        b.edge(g_log_bot, r_log)
        g_repl = b.goal("replicate", [rep, payload, primary, client], log_time - 1)
        b.edge(r_log, g_repl)

        # replicate@async :- request, replica: network hop primary -> replica.
        r_repl = b.rule("replicate", "async")
        b.edge(g_repl, r_repl)
        if g_req is None:
            g_req = b.goal("request", [primary, payload, client], 1)
        b.edge(r_repl, g_req)
        b.edge(r_repl, b.goal("replica", [primary, rep], 1))
        b.edge(r_repl, b.clock_goal(primary, rep, log_time - 1))

    return b.build()


def build_spacetime_dot(
    nodes: list[str],
    eot: int,
    messages: list[dict[str, Any]],
    crashes: dict[str, int] | None = None,
) -> str:
    """Space-time DOT diagram in the shape hazard analysis parses: node names
    end in _<timestep> (reference: graphing/hazard-analysis.go:48-54), with
    each process's timeline wrapped in a `subgraph cluster_<n>` block — the
    structure Molly emits and the reference's gographviz parse + `dot -Tsvg`
    pipeline renders as per-process boxes.  A crashed process's clock edges
    stop at its crash time.  Shared by the synthetic generators and the
    mini-Dedalus fault injector."""
    crashes = crashes or {}
    lines = ["digraph spacetime {"]
    for n in nodes:
        last = crashes.get(n, eot)
        lines.append(f'\tsubgraph "cluster_{n}" {{')
        lines.append(f'\t\tlabel="process {n}";')
        for t in range(1, eot + 1):
            label = f"{n}@{t}" + (" CRASHED" if n in crashes and t >= last else "")
            lines.append(f'\t\t"{n}_{t}" [label="{label}"];')
        for t in range(1, min(last, eot)):
            lines.append(f'\t\t"{n}_{t}" -> "{n}_{t + 1}";')
        lines.append("\t}")
    for m in messages:
        if m["sendTime"] < eot:
            lines.append(f'\t"{m["from"]}_{m["sendTime"]}" -> "{m["to"]}_{m["receiveTime"]}";')
    lines.append("}")
    return "\n".join(lines)


_build_spacetime_dot = build_spacetime_dot  # module-internal callers


@dataclass
class SynthSpec:
    """Configuration for one synthetic corpus."""

    n_runs: int = 4
    seed: int = 0
    eot: int = 6
    eff: int = 4
    name: str = "pb_synth"
    # Fraction of runs (beyond run 0, which always succeeds) per kind.
    fail_fraction: float = 0.4
    vacuous_fraction: float = 0.2
    # A total replication failure: every replicate message lost, so the failed
    # run's consequent provenance is empty and whole rule tables go missing.
    fail_all_fraction: float = 0.15
    # Kind forced on run 0.  Molly puts the failure-free execution first, and
    # the reference relies on that (differential-provenance.go:22); set to
    # "fail" to exercise the rebuild's good-run selection guard.
    first_run_kind: str = "success"
    # Adversarial graph family (ISSUE 15): "pb" is the standard
    # primary/backup protocol above; the ADVERSARIAL_FAMILIES values warp
    # it into the shapes that stress specific analysis machinery — see
    # each family's note at adversarial_spec().
    family: str = "pb"
    # deep_chain: @next persistence-chain length (eot is raised to fit).
    depth: int = 64
    # wide_fanout: replica count (one post <- log branch per replica).
    fanout: int = 16
    # vocab_growth: fresh goal/rule tables EVERY run adds to the corpus
    # vocabulary.
    vocab_per_run: int = 6


def _gen_run(spec: SynthSpec, rng: random.Random, i: int) -> tuple[dict, dict[str, Any]]:
    """Generate ONE run: (its runs.json entry, its three files).  Consumes
    the rng in a fixed order, so the streaming writer and the in-memory
    generator produce identical corpora for identical (seed, index)
    sequences.  Adversarial families (spec.family) warp the protocol shape
    but keep the exact Molly schema, so every downstream layer analyzes
    them unchanged."""
    client, primary = "C", "a"
    if spec.family == "wide_fanout":
        # One consequent goal fanning out to `fanout` log branches: the
        # scatter/gather frontier kernels' widest single wave.
        replicas = [f"r{k}" for k in range(max(2, spec.fanout))]
    else:
        replicas = ["b", "c"]
    nodes = [client, primary] + replicas
    payload = "foo"

    if i == 0:
        kind = spec.first_run_kind
    else:
        u = rng.random()
        if u < spec.fail_fraction:
            kind = "fail"
        elif u < spec.fail_fraction + spec.vacuous_fraction:
            kind = "vacuous"
        elif u < spec.fail_fraction + spec.vacuous_fraction + spec.fail_all_fraction:
            kind = "fail_all"
        else:
            kind = "success"

    eot = spec.eot
    if spec.family == "deep_chain":
        # The collapseNextChains worst case at corpus scale: every run's
        # pre/post chains span `depth` timesteps.
        eot = max(eot, spec.depth + 3)
    if spec.family == "near_dup":
        # Near-duplicate runs: times pinned so consecutive runs differ in
        # nothing but iteration (and one in four by a single timestep) —
        # the render-dedup / result-cache aliasing stress.  The rng is
        # still consumed (below) so the corpus prefix stays stable if the
        # family is toggled.
        _, _ = rng.randint(3, max(3, eot - 2)), rng.randint(3, max(3, eot - 1))
        ack_time, log_time = 3, 4 + (1 if i % 4 == 3 else 0)
    else:
        ack_time = rng.randint(3, max(3, eot - 2))
        log_time = rng.randint(3, max(3, eot - 1))
    if spec.family == "deep_chain":
        # Pin the chain bottoms low: the chains (eot -> ack/log time) then
        # span ~depth steps regardless of the rng draw above.
        ack_time, log_time = 3, 3

    omissions: list[dict[str, Any]] = []
    crashes: list[dict[str, Any]] = []

    if kind == "fail":
        # Lose the replicate message to one replica.
        lost = rng.choice(replicas)
        logged = [r for r in replicas if r != lost]
        omissions.append({"from": primary, "to": lost, "time": log_time - 1})
        pre_achieved, post_achieved = True, False
        status = "fail"
    elif kind == "fail_all":
        # Lose every replicate message: the ack still happens (async
        # primary/backup acks before replicating) but the consequent
        # provenance is empty and whole rule tables go missing.
        logged = []
        for rep in replicas:
            omissions.append({"from": primary, "to": rep, "time": log_time - 1})
        pre_achieved, post_achieved = True, False
        status = "fail"
    elif kind == "vacuous":
        # Lose the initial request: antecedent never achieved.
        logged = []
        omissions.append({"from": client, "to": primary, "time": 1})
        pre_achieved, post_achieved = False, False
        status = "success"
    else:
        logged = list(replicas)
        pre_achieved, post_achieved = True, True
        status = "success"

    messages = [
        {"table": "request", "from": client, "to": primary, "sendTime": 1, "receiveTime": 2},
    ]
    if pre_achieved:
        messages.append(
            {
                "table": "ack",
                "from": primary,
                "to": client,
                "sendTime": ack_time - 1,
                "receiveTime": ack_time,
            }
        )
        for rep in logged:
            messages.append(
                {
                    "table": "replicate",
                    "from": primary,
                    "to": rep,
                    "sendTime": log_time - 1,
                    "receiveTime": log_time,
                }
            )

    # Model tables: last column of each 'pre'/'post' row is the timestep at
    # which the condition held (faultinjectors/molly.go:38-48).
    tables: dict[str, list[list[str]]] = {"pre": [], "post": []}
    if pre_achieved:
        tables["pre"] = [[payload, str(t)] for t in range(ack_time, eot + 1)]
    if post_achieved:
        tables["post"] = [[payload, str(t)] for t in range(log_time, eot + 1)]

    entry = {
        "iteration": i,
        "status": status,
        "failureSpec": {
            "eot": eot,
            "eff": spec.eff,
            "maxCrashes": 1,
            "nodes": nodes,
            "crashes": crashes,
            "omissions": omissions,
        },
        "model": {"tables": tables},
        "messages": messages,
    }
    pre_prov = _build_pre_prov(pre_achieved, eot, ack_time, client, primary, payload)
    post_prov = _build_post_prov(
        logged, eot, log_time, post_achieved, primary, client, payload
    )
    if spec.family == "vocab_growth":
        _grow_vocab(pre_prov, i, spec.vocab_per_run)
    elif spec.family == "cycles":
        _add_cycle(post_prov, i)
    files = {
        f"run_{i}_pre_provenance.json": pre_prov,
        f"run_{i}_post_provenance.json": post_prov,
        f"run_{i}_spacetime.dot": _build_spacetime_dot(nodes, eot, messages),
    }
    return entry, files


def _grow_vocab(prov: dict[str, Any], i: int, n: int) -> None:
    """Pathological vocabulary growth (adversarial family): hang ``n``
    goals with RUN-UNIQUE table/label/time strings off the graph's first
    goal.  Every run then grows the corpus vocabularies linearly — the
    stress for vocab interning, store vocab generations, and any
    [T]-shaped kernel plane."""
    base = prov["goals"][0]["id"] if prov["goals"] else None
    for j in range(n):
        g = {
            "id": f"aux_g_{i}_{j}",
            "label": f"aux_{i}_{j}(v{j}, t{i})",
            "table": f"aux_{i}_{j}",
            "time": str(10 + i),
        }
        r = {
            "id": f"aux_r_{i}_{j}",
            "label": f"aux_rule_{i}_{j}",
            "table": f"aux_rule_{i}_{j}",
            "type": "",
        }
        prov["goals"].append(g)
        prov["rules"].append(r)
        prov["edges"].append({"from": g["id"], "to": r["id"]})
        if base is not None:
            prov["edges"].append({"from": r["id"], "to": base})


def _add_cycle(prov: dict[str, Any], i: int) -> None:
    """Schema-valid provenance CYCLE (adversarial family): goal -> rule ->
    goal -> rule -> back to the first goal, attached under the graph's
    first rule when one exists.  Exercises every fix-point loop's
    termination (the sparse-device diff's capped max-plus sweep, the host
    relaxation, dense closure) — a depth-bounded wave that assumed a DAG
    would spin or truncate here."""
    anchor = prov["rules"][0]["id"] if prov["rules"] else None
    g0 = {"id": f"cyc_g0_{i}", "label": f"cyc({i}, a)", "table": "cyc", "time": "2"}
    g1 = {"id": f"cyc_g1_{i}", "label": f"cyc({i}, b)", "table": "cyc", "time": "3"}
    r0 = {"id": f"cyc_r0_{i}", "label": "cyc_step", "table": "cyc_step", "type": ""}
    r1 = {"id": f"cyc_r1_{i}", "label": "cyc_step", "table": "cyc_step", "type": ""}
    prov["goals"] += [g0, g1]
    prov["rules"] += [r0, r1]
    prov["edges"] += [
        {"from": g0["id"], "to": r0["id"]},
        {"from": r0["id"], "to": g1["id"]},
        {"from": g1["id"], "to": r1["id"]},
        {"from": r1["id"], "to": g0["id"]},
    ]
    if anchor is not None:
        prov["edges"].append({"from": anchor, "to": g0["id"]})


def generate_corpus(spec: SynthSpec) -> dict[str, Any]:
    """Generate an in-memory corpus: file name -> JSON-serializable content.

    By default run 0 succeeds with full replication — the reference assumes
    the first run is the successful one everywhere it hardcodes run 0
    (e.g. graphing/corrections.go:210-216, differential-provenance.go:26).
    Override with spec.first_run_kind to test that assumption's guard.
    """
    rng = random.Random(spec.seed)
    files: dict[str, Any] = {}
    runs_json = []
    for i in range(spec.n_runs):
        entry, run_files = _gen_run(spec, rng, i)
        runs_json.append(entry)
        files.update(run_files)
    files["runs.json"] = runs_json
    return files


def write_corpus(spec: SynthSpec, out_dir: str) -> str:
    """Write a generated corpus as a Molly output directory; returns its path."""
    corpus_dir = os.path.join(out_dir, spec.name)
    os.makedirs(corpus_dir, exist_ok=True)
    for name, content in generate_corpus(spec).items():
        path = os.path.join(corpus_dir, name)
        with open(path, "w", encoding="utf-8") as f:
            if name.endswith(".json"):
                json.dump(content, f, indent=1)
            else:
                f.write(content)
    return corpus_dir


def grow_corpus_dir(full_dir: str, dst_dir: str, n_runs: int) -> None:
    """Materialize the first ``n_runs`` runs of an already-written corpus
    (synth or case-study layout: run_<i>_{pre,post}_provenance.json,
    run_<i>_spacetime.dot, runs.json) into ``dst_dir``.  Monotonic: call
    again with a larger ``n_runs`` to grow the directory the way a
    still-running Molly sweep appends runs — existing run files are left
    untouched (their mtimes, and so the store's fingerprints, stay stable);
    only runs.json is rewritten.  The incremental-sweep simulator shared by
    the delta smoke and the bench delta tier."""
    import shutil

    os.makedirs(dst_dir, exist_ok=True)
    with open(os.path.join(full_dir, "runs.json"), encoding="utf-8") as fh:
        raw = json.load(fh)
    for i in range(n_runs):
        for c in ("pre", "post"):
            name = f"run_{i}_{c}_provenance.json"
            dst = os.path.join(dst_dir, name)
            if not os.path.exists(dst):
                shutil.copy2(os.path.join(full_dir, name), dst)
        st = f"run_{i}_spacetime.dot"
        src = os.path.join(full_dir, st)
        dst = os.path.join(dst_dir, st)
        if os.path.exists(src) and not os.path.exists(dst):
            shutil.copy2(src, dst)
    with open(os.path.join(dst_dir, "runs.json"), "w", encoding="utf-8") as fh:
        json.dump(raw[:n_runs], fh, indent=1)


def _append_entries(path: str, new_entries: list[str], first: bool) -> None:
    """Flush one segment's pre-serialized runs.json entries, byte-identical
    to rewriting ``json.dump(all_entries, fh, indent=1)`` — the serializer
    grow_corpus_dir and the store's strong runs.json prefix check
    (npack._runs_prefix_sha) pin.  Because each flush keeps the previous
    one as an exact byte prefix (sans the closing ``\\n]``), later segments
    APPEND IN PLACE — seek back over the two tail bytes and write only the
    new entries — so flushing the whole corpus costs O(total) bytes once,
    not O(segments * total), and no entry outlives its segment in memory."""
    if first:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("[")
            for j, e in enumerate(new_entries):
                fh.write(",\n " if j else "\n ")
                fh.write(e)
            fh.write("\n]")
        return
    with open(path, "r+b") as fh:
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        fh.seek(max(0, size - 2))
        tail = fh.read(2)
        if tail != b"\n]":
            raise RuntimeError(
                f"{path}: unexpected tail {tail!r} (not a prior segment flush)"
            )
        fh.seek(size - 2)
        buf = "".join(",\n " + e for e in new_entries) + "\n]"
        fh.write(buf.encode("utf-8"))
        fh.truncate()


def write_corpus_stream(
    spec: SynthSpec,
    out_dir: str,
    segment_runs: int,
    store=None,
    log=None,
) -> str:
    """Write a ``spec.n_runs`` corpus SEGMENT BY SEGMENT — the million-run
    generator (ISSUE 12, extending :func:`grow_corpus_dir`'s incremental-
    sweep simulation to generation itself).  Each segment's run files are
    written and runs.json re-flushed (the previous content stays a byte
    prefix), then — when ``store`` (a CorpusStore) is passed — the corpus
    store is populated/appended immediately, producing a genuinely
    multi-segment ``.npack`` whose segment boundaries are exactly these
    generation batches.  Generation memory and per-segment flush cost are
    O(segment) — later segments append to runs.json in place
    (:func:`_append_entries`) — and the per-run provenance content is
    identical to :func:`generate_corpus` at the same seed.

    Returns the corpus directory."""
    corpus_dir = os.path.join(out_dir, spec.name)
    os.makedirs(corpus_dir, exist_ok=True)
    rng = random.Random(spec.seed)
    runs_path = os.path.join(corpus_dir, "runs.json")
    if spec.n_runs == 0:
        with open(runs_path, "w", encoding="utf-8") as fh:
            fh.write("[]")
    i = 0
    while i < spec.n_runs:
        seg_end = min(spec.n_runs, i + segment_runs)
        seg_entries: list[str] = []  # this segment's entries only
        for j in range(i, seg_end):
            entry, files = _gen_run(spec, rng, j)
            # Continuation lines gain the list level's one-space indent;
            # safe textually because json.dumps escapes newlines inside
            # strings, so raw "\n" is always formatting.
            seg_entries.append(json.dumps(entry, indent=1).replace("\n", "\n "))
            for name, content in files.items():
                path = os.path.join(corpus_dir, name)
                with open(path, "w", encoding="utf-8") as f:
                    if name.endswith(".json"):
                        json.dump(content, f, indent=1)
                    else:
                        f.write(content)
        _append_entries(runs_path, seg_entries, first=(i == 0))
        if store is not None:
            # First segment: parse + populate.  Later segments: the grown
            # directory classifies GROWN and appends ONLY the new runs
            # (store/__init__._append_locked); load_corpus skips the
            # per-run MollyOutput construction, so the per-segment store
            # maintenance is array-and-parse work over the segment alone.
            got = store.load_corpus(corpus_dir)
            if got is None:
                from nemo_tpu.ingest.molly import load_molly_output

                store.put(corpus_dir, load_molly_output(corpus_dir))
        if log is not None:
            log(f"  synth stream: {seg_end}/{spec.n_runs} runs written")
        i = seg_end
    return corpus_dir


# ---------------------------------------------------------------------------
# adversarial graph families (ISSUE 15)
# ---------------------------------------------------------------------------

#: Named adversarial families — first-class bench tiers (bench.py
#: `adversarial_tier`) and the workloads items 2/5's tuning targets.  Each
#: stresses a specific subsystem; all keep the exact Molly schema, so the
#: whole stack (store, delta, sparse kernels, synthesis, serving, watch)
#: analyzes them unchanged.
ADVERSARIAL_FAMILIES: tuple[str, ...] = (
    "deep_chain",    # ~depth-step @next chains per run: chain collapse,
                     # frontier-wave depth, giant-path routing
    "wide_fanout",   # one consequent goal, `fanout` log branches: widest
                     # single scatter/gather wave, edge-bucket blowup
    "near_dup",      # isomorphic-run floods: render dedup, rcache
                     # aliasing, figure-cache correctness under near-misses
    "vocab_growth",  # run-unique tables/labels/times: vocab interning,
                     # store vocab generations, [T]-plane growth
    "cycles",        # schema-valid provenance cycles: every fix-point
                     # loop's termination (no DAG assumption survives)
)


def adversarial_spec(
    family: str, n_runs: int = 8, seed: int = 0, **overrides
) -> SynthSpec:
    """A ready-to-write SynthSpec for one adversarial family (plus "pb"
    for the baseline).  Deterministic per (family, n_runs, seed) — the
    generator-determinism tests pin exactly that."""
    if family != "pb" and family not in ADVERSARIAL_FAMILIES:
        raise ValueError(
            f"unknown adversarial family {family!r} "
            f"(expected pb, {', '.join(ADVERSARIAL_FAMILIES)})"
        )
    kw: dict[str, Any] = dict(
        n_runs=n_runs, seed=seed, name=f"adv_{family}", family=family
    )
    if family == "deep_chain":
        kw["depth"] = 64
    elif family == "wide_fanout":
        kw["fanout"] = 24
    kw.update(overrides)
    return SynthSpec(**kw)


# The shared 10k-node giant-path stress scenario (VERDICT r3 task 7): a
# ~3000-step @next chain — the reference's collapseNextChains worst case
# (preprocessing.go:253-353) at ~1000x its case-study depth.  One definition
# so bench.py, giant_profile.py, and tests/test_giant.py measure the SAME
# workload; NEMO_GIANT_V must stay at its 4096 default (below the ~10k node
# count) for the run to take the giant path.
GIANT10K_THRESHOLD_V = 4096


def giant10k_spec() -> SynthSpec:
    return SynthSpec(n_runs=2, seed=2, eot=3000, name="giant10k")
