"""The six case-study protocol families, as synthetic Molly corpora.

The reference ships six Dedalus case studies that Molly model-checks and Nemo
debugs (reference: case-studies/*.ded, 6 files; each header carries the Molly
invocation bounds — EOT 6-8, EFF 3-5, <=1 crash, 2-4 nodes, per SURVEY.md §2).
Molly itself is not available in this environment, so each family here is a
deterministic generator of Molly-format output directories (the schema of
faultinjectors/data-types.go:6-98) with that family's protocol vocabulary,
topology, bounds, and fault mode:

  * pb_asynchronous          (case-studies/pb_asynchronous.ded:62-63)
    async primary/backup: ack before replication; lost replicate violates
    "payload logged on all correct replicas".
  * CA-2083-hinted-handoff   (case-studies/CA-2083-hinted-handoff.ded:23-24)
    Cassandra hinted handoff: coordinator acks a write, stores hints for a
    crashed replica; a lost replay leaves the write un-stored. Crash faults.
  * CA-2434-bootstrap-synchronization
                             (case-studies/CA-2434-bootstrap-synchronization.ded:27-28)
    Cassandra bootstrap: a joining node must receive every key range from its
    peers before serving.
  * MR-2995-failed-after-expiry
                             (case-studies/MR-2995-failed-after-expiry.ded:27-28)
    MapReduce: tasks assigned to workers must complete even when a worker
    fails after its lease expiry. Crash faults, 4 nodes.
  * MR-3858-hadoop           (case-studies/MR-3858-hadoop.ded:31-32)
    Hadoop write pipeline: an acked block must be stored on every datanode.
  * ZK-1270-racing-sent-flag (case-studies/ZK-1270-racing-sent-flag.ded:32-33)
    ZooKeeper: the leader's sent-flag is raised concurrently with the commit
    broadcast (modeled as an extra @next flag chain in the antecedent
    provenance); a lost commit leaves a follower uncommitted.

All families share the protocol *shape* (antecedent = client acked;
consequent = payload persisted on all targets) because that is the shape of
the reference invariants; they differ in vocabulary, topology, timing bounds,
fault mode, and graph structure — which is exactly what exercises
vocabulary-keyed analyses (prototypes, diff-by-label) across corpora.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any

from nemo_tpu.models.synth import ProvBuilder, _build_spacetime_dot


@dataclass(frozen=True)
class FamilySpec:
    """One case-study family: protocol vocabulary + topology + bounds."""

    name: str
    ref: str  # the reference .ded file this family models
    eot: int
    eff: int
    max_crashes: int
    client: str
    coordinator: str
    targets: tuple[str, ...]
    payload: str
    begin_table: str  # client-local start fact
    request_table: str  # client -> coordinator @async
    ack_table: str  # coordinator -> client @async
    acked_table: str  # @next persistence chain of the ack (antecedent)
    propagate_table: str  # coordinator -> target @async
    persist_table: str  # @next persistence chain on the target (consequent)
    member_table: str  # static membership fact joined by propagation
    conn_table: str = "conn_out"
    crash_faults: bool = False  # failed runs crash a target instead of losing a message
    flag_chain_table: str | None = None  # ZK-1270: racing sent-flag @next chain


CASE_STUDIES: dict[str, FamilySpec] = {
    s.name: s
    for s in [
        FamilySpec(
            name="pb_asynchronous",
            ref="case-studies/pb_asynchronous.ded",
            eot=6, eff=4, max_crashes=1,
            client="C", coordinator="a", targets=("b", "c"), payload="foo",
            begin_table="begin", request_table="request", ack_table="ack",
            acked_table="acked", propagate_table="replicate",
            persist_table="log", member_table="replica",
        ),
        FamilySpec(
            name="CA-2083-hinted-handoff",
            ref="case-studies/CA-2083-hinted-handoff.ded",
            eot=7, eff=4, max_crashes=1,
            client="C", coordinator="co", targets=("r1", "r2"), payload="v1",
            begin_table="write_req", request_table="write", ack_table="write_ack",
            acked_table="client_acked", propagate_table="hint_replay",
            persist_table="stored", member_table="replica_of",
            crash_faults=True,
        ),
        FamilySpec(
            name="CA-2434-bootstrap-synchronization",
            ref="case-studies/CA-2434-bootstrap-synchronization.ded",
            eot=8, eff=5, max_crashes=1,
            client="n", coordinator="seed", targets=("p1", "p2"), payload="range0",
            begin_table="join_req", request_table="join", ack_table="join_ack",
            acked_table="joined", propagate_table="stream_range",
            persist_table="range_synced", member_table="ring_member",
        ),
        FamilySpec(
            name="MR-2995-failed-after-expiry",
            ref="case-studies/MR-2995-failed-after-expiry.ded",
            eot=8, eff=4, max_crashes=1,
            client="J", coordinator="jt", targets=("w1", "w2"), payload="job1",
            begin_table="submit_req", request_table="submit", ack_table="submit_ack",
            acked_table="accepted", propagate_table="assign",
            persist_table="task_done", member_table="worker",
            crash_faults=True,
        ),
        FamilySpec(
            name="MR-3858-hadoop",
            ref="case-studies/MR-3858-hadoop.ded",
            eot=7, eff=3, max_crashes=1,
            client="C", coordinator="nn", targets=("d1", "d2"), payload="blk_1",
            begin_table="put_req", request_table="put", ack_table="put_ack",
            acked_table="client_ok", propagate_table="pipeline_write",
            persist_table="block_stored", member_table="datanode",
        ),
        FamilySpec(
            name="ZK-1270-racing-sent-flag",
            ref="case-studies/ZK-1270-racing-sent-flag.ded",
            eot=8, eff=5, max_crashes=1,
            client="C", coordinator="L", targets=("f1", "f2"), payload="txn7",
            begin_table="txn_req", request_table="propose", ack_table="prop_ack",
            acked_table="proposed", propagate_table="commit_msg",
            persist_table="committed", member_table="follower",
            flag_chain_table="sent_flag",
        ),
    ]
}


def _pre_prov(spec: FamilySpec, achieved: bool, ack_time: int) -> dict[str, Any]:
    """Antecedent provenance:
    pre <- <acked chain> <- <ack rule @async> <- <request rule @async>."""
    b = ProvBuilder()
    client, coord, payload = spec.client, spec.coordinator, spec.payload
    if not achieved:
        g_begin = b.goal(spec.begin_table, [client, payload], 1)
        r_begin = b.rule(spec.begin_table)
        b.edge(g_begin, r_begin)
        b.edge(r_begin, b.clock_goal(client, client, 1))
        return b.build()

    g_pre = b.goal("pre", [payload], spec.eot)
    r_pre = b.rule("pre")
    b.edge(g_pre, r_pre)

    g_top, g_bot = b.next_chain(spec.acked_table, [client, coord, payload], spec.eot, ack_time)
    b.edge(r_pre, g_top)

    r_acked = b.rule(spec.acked_table)
    b.edge(g_bot, r_acked)
    g_ack = b.goal(spec.ack_table, [client, coord, payload], ack_time)
    b.edge(r_acked, g_ack)

    if spec.flag_chain_table:
        # ZK-1270: the racing sent-flag — a parallel @next chain the acked
        # deduction also depends on, raised concurrently with the broadcast.
        f_top, f_bot = b.next_chain(spec.flag_chain_table, [coord, payload], spec.eot, ack_time)
        b.edge(r_pre, f_top)
        r_flag = b.rule(spec.flag_chain_table)
        b.edge(f_bot, r_flag)
        b.edge(r_flag, b.clock_goal(coord, coord, ack_time - 1))

    r_ack = b.rule(spec.ack_table, "async")
    b.edge(g_ack, r_ack)
    g_req = b.goal(spec.request_table, [coord, payload, client], ack_time - 1)
    b.edge(r_ack, g_req)
    b.edge(r_ack, b.clock_goal(coord, client, ack_time - 1))

    r_req = b.rule(spec.request_table, "async")
    b.edge(g_req, r_req)
    b.edge(r_req, b.goal(spec.begin_table, [client, payload], 1))
    b.edge(r_req, b.goal(spec.conn_table, [client, coord], 1))
    b.edge(r_req, b.clock_goal(client, coord, 1))
    return b.build()


def _post_prov(
    spec: FamilySpec, persisted: list[str], persist_time: int, achieved: bool
) -> dict[str, Any]:
    """Consequent provenance:
    post <- <persist chain per target> <- <propagate rule @async>."""
    b = ProvBuilder()
    coord, client, payload = spec.coordinator, spec.client, spec.payload
    r_post = None
    if achieved:
        g_post = b.goal("post", [payload], spec.eot)
        r_post = b.rule("post")
        b.edge(g_post, r_post)

    g_req = None
    for tgt in persisted:
        g_top, g_bot = b.next_chain(spec.persist_table, [tgt, payload], spec.eot, persist_time)
        if r_post is not None:
            b.edge(r_post, g_top)

        r_persist = b.rule(spec.persist_table)
        b.edge(g_bot, r_persist)
        g_prop = b.goal(spec.propagate_table, [tgt, payload, coord, client], persist_time - 1)
        b.edge(r_persist, g_prop)

        r_prop = b.rule(spec.propagate_table, "async")
        b.edge(g_prop, r_prop)
        if g_req is None:
            g_req = b.goal(spec.request_table, [coord, payload, client], 1)
        b.edge(r_prop, g_req)
        b.edge(r_prop, b.goal(spec.member_table, [coord, tgt], 1))
        b.edge(r_prop, b.clock_goal(coord, tgt, persist_time - 1))
    return b.build()


def generate_case_study(spec: FamilySpec, n_runs: int, seed: int = 0) -> dict[str, Any]:
    """In-memory Molly corpus for one family: file name -> content.

    Run 0 always succeeds with full propagation (the reference hardcodes run 0
    as the good run, e.g. graphing/corrections.go:210-216).  Failed runs
    either lose one propagation (message omission, or a target crash when the
    family's fault mode is crashes), lose all propagations, or lose the
    initial request (vacuous success: antecedent never achieved).
    """
    # str seeds hash via sha512 in random.seed — stable across processes
    # (tuple.__hash__ would be salted by PYTHONHASHSEED).
    rng = random.Random(f"{seed}:{spec.name}")
    nodes = [spec.client, spec.coordinator, *spec.targets]
    files: dict[str, Any] = {}
    runs_json = []

    for i in range(n_runs):
        if i == 0:
            kind = "success"
        else:
            u = rng.random()
            kind = (
                "fail" if u < 0.4 else
                "vacuous" if u < 0.6 else
                "fail_all" if u < 0.75 else
                "success"
            )

        ack_time = rng.randint(3, max(3, spec.eot - 2))
        # Faults fire at persist_time - 1, and Molly only injects faults at
        # times <= EFF (the failure window in the .ded headers) — keep the
        # generated failureSpec self-consistent by bounding the draw.
        persist_time = rng.randint(3, max(3, min(spec.eot - 1, spec.eff + 1)))
        omissions: list[dict[str, Any]] = []
        crashes: list[dict[str, Any]] = []

        if kind == "fail":
            lost = rng.choice(list(spec.targets))
            persisted = [t for t in spec.targets if t != lost]
            if spec.crash_faults:
                crashes.append({"node": lost, "time": persist_time - 1})
            else:
                omissions.append(
                    {"from": spec.coordinator, "to": lost, "time": persist_time - 1}
                )
            pre_achieved, post_achieved, status = True, False, "fail"
        elif kind == "fail_all":
            # Crash-fault families crash one target (respecting maxCrashes=1)
            # and lose the remaining propagations; omission families lose all.
            persisted = []
            for k, tgt in enumerate(spec.targets):
                if spec.crash_faults and k == 0:
                    crashes.append({"node": tgt, "time": persist_time - 1})
                else:
                    omissions.append(
                        {"from": spec.coordinator, "to": tgt, "time": persist_time - 1}
                    )
            pre_achieved, post_achieved, status = True, False, "fail"
        elif kind == "vacuous":
            persisted = []
            omissions.append({"from": spec.client, "to": spec.coordinator, "time": 1})
            pre_achieved, post_achieved, status = False, False, "success"
        else:
            persisted = list(spec.targets)
            pre_achieved, post_achieved, status = True, True, "success"

        messages = [
            {
                "table": spec.request_table,
                "from": spec.client,
                "to": spec.coordinator,
                "sendTime": 1,
                "receiveTime": 2,
            }
        ]
        if pre_achieved:
            messages.append(
                {
                    "table": spec.ack_table,
                    "from": spec.coordinator,
                    "to": spec.client,
                    "sendTime": ack_time - 1,
                    "receiveTime": ack_time,
                }
            )
            for tgt in persisted:
                messages.append(
                    {
                        "table": spec.propagate_table,
                        "from": spec.coordinator,
                        "to": tgt,
                        "sendTime": persist_time - 1,
                        "receiveTime": persist_time,
                    }
                )

        tables: dict[str, list[list[str]]] = {"pre": [], "post": []}
        if pre_achieved:
            tables["pre"] = [[spec.payload, str(t)] for t in range(ack_time, spec.eot + 1)]
        if post_achieved:
            tables["post"] = [[spec.payload, str(t)] for t in range(persist_time, spec.eot + 1)]

        runs_json.append(
            {
                "iteration": i,
                "status": status,
                "failureSpec": {
                    "eot": spec.eot,
                    "eff": spec.eff,
                    "maxCrashes": spec.max_crashes,
                    "nodes": nodes,
                    "crashes": crashes,
                    "omissions": omissions,
                },
                "model": {"tables": tables},
                "messages": messages,
            }
        )
        files[f"run_{i}_pre_provenance.json"] = _pre_prov(spec, pre_achieved, ack_time)
        files[f"run_{i}_post_provenance.json"] = _post_prov(
            spec, persisted, persist_time, post_achieved
        )
        files[f"run_{i}_spacetime.dot"] = _build_spacetime_dot(nodes, spec.eot, messages)

    files["runs.json"] = runs_json
    return files


def write_case_study(name: str, n_runs: int, seed: int, out_dir: str) -> str:
    """Write one family's corpus as a Molly output directory; returns its path."""
    import json
    import os

    spec = CASE_STUDIES[name]
    corpus_dir = os.path.join(out_dir, spec.name)
    os.makedirs(corpus_dir, exist_ok=True)
    for fname, content in generate_case_study(spec, n_runs, seed).items():
        path = os.path.join(corpus_dir, fname)
        with open(path, "w", encoding="utf-8") as f:
            if fname.endswith(".json"):
                json.dump(content, f, indent=1)
            else:
                f.write(content)
    return corpus_dir


def write_all_case_studies(n_runs: int, seed: int, out_dir: str) -> dict[str, str]:
    """Write every family; returns name -> corpus directory."""
    return {name: write_case_study(name, n_runs, seed, out_dir) for name in CASE_STUDIES}
