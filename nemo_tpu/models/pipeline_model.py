"""The flagship model: one fused, jittable analysis step over a run batch.

This is the framework's equivalent of a model's training step — the unit the
benchmark times and the driver compile-checks.  Given the packed pre/post
provenance batches of B fault-injection runs (both padded to one bucket), a
single jit region computes everything the per-run Cypher pipeline of the
reference produces (main.go:106-180): condition marking for both conditions,
clean-copy + @next chain contraction, prototype bitsets with cross-run
intersection/union reductions, and differential provenance of every run
against the successful run in row 0.  Under a sharded mesh the run axis is
data-parallel and the cross-run reductions become ICI all-reduces.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from nemo_tpu.graphs.packed import CorpusVocab, pack_batch, pack_graph
from nemo_tpu.ingest.molly import MollyOutput
from nemo_tpu.ops.adjacency import build_adjacency
from nemo_tpu.ops.condition import mark_condition_holds
from nemo_tpu.ops.diff import diff_masks
from nemo_tpu.ops.proto import all_rule_bits, proto_rule_bits, reduce_protos
from nemo_tpu.ops.simplify import clean_masks, collapse_chains


@dataclass
class BatchArrays:
    """Device-ready arrays for one condition's run batch."""

    edge_src: jnp.ndarray
    edge_dst: jnp.ndarray
    edge_mask: jnp.ndarray
    is_goal: jnp.ndarray
    table_id: jnp.ndarray
    label_id: jnp.ndarray
    type_id: jnp.ndarray
    node_mask: jnp.ndarray

    # Field-name tuple for generic row slicing/padding (not a dataclass
    # field: no annotation).
    FIELDS = (
        "edge_src",
        "edge_dst",
        "edge_mask",
        "is_goal",
        "table_id",
        "label_id",
        "type_id",
        "node_mask",
    )

    @classmethod
    def from_packed(cls, batch) -> "BatchArrays":
        return cls(
            edge_src=jnp.asarray(batch.edge_src),
            edge_dst=jnp.asarray(batch.edge_dst),
            edge_mask=jnp.asarray(batch.edge_mask),
            is_goal=jnp.asarray(batch.is_goal),
            table_id=jnp.asarray(batch.table_id),
            label_id=jnp.asarray(batch.label_id),
            type_id=jnp.asarray(batch.type_id),
            node_mask=jnp.asarray(batch.node_mask),
        )


jax.tree_util.register_dataclass(
    BatchArrays,
    data_fields=[
        "edge_src",
        "edge_dst",
        "edge_mask",
        "is_goal",
        "table_id",
        "label_id",
        "type_id",
        "node_mask",
    ],
    meta_fields=[],
)


# analysis_step outputs that are reductions over the run axis, not per-run
# rows, and how to re-combine them across chunked batches (used by the
# sidecar client's chunk merge).  Keep in sync with the return dict below:
# any new cross-run reduction output MUST be added here.
CORPUS_REDUCTIONS = {"proto_inter": "and", "proto_union": "or"}

# The bool summary outputs folded into one bit-packed device->host transfer
# under pack_out=True, in pack order; the shape key resolves with b=batch,
# v=nodes, t=num_tables (backend/jax_backend.py:_unpack_summary is the
# inverse).  Every entry must be a bool output of the with_diff=False
# return dict.
SUMMARY_PACK_LAYOUT = (
    ("pre_holds", "bv"),
    ("post_holds", "bv"),
    ("achieved_pre", "b"),
    ("proto_bits", "bt"),
    ("proto_present", "bt"),
    ("proto_inter", "t"),
    ("proto_union", "t"),
)

# The diff-tail bool outputs appended to the folded transfer when
# with_diff=True (the sidecar Analyze path; all [B,V]).
DIFF_PACK_LAYOUT = (
    ("diff_node_keep", "bv"),
    ("diff_frontier_rule", "bv"),
    ("diff_missing_goal", "bv"),
)

# The giant verb's folded layout (parallel/giant.py): its fused-compatible
# output set has no proto_inter/proto_union — the backend merges giant
# prototype bitsets with the dense buckets' host-side.
GIANT_PACK_LAYOUT = (
    ("pre_holds", "bv"),
    ("post_holds", "bv"),
    ("achieved_pre", "b"),
    ("proto_bits", "bt"),
    ("proto_present", "bt"),
)


def fold_packed_summary(out: dict, layout) -> None:
    """Replace `layout`'s bool outputs in `out` with one bit-packed
    "packed_summary" vector, in place.  Must run INSIDE the compiled
    program (a separate pack dispatch would pay its own tunnel RTT);
    backend/jax_backend.py:_unpack_summary is the inverse, keyed by the
    same layout tuple."""
    out["packed_summary"] = jnp.packbits(
        jnp.concatenate([out.pop(name).ravel() for name, _ in layout])
    )


def analysis_step(
    pre: BatchArrays,
    post: BatchArrays,
    v: int,
    pre_tid: int,
    post_tid: int,
    num_tables: int,
    num_labels: int,
    max_depth: int,
    closure_impl: str = "auto",
    with_diff: bool = True,
    comp_linear: bool = False,
    pack_out: bool = False,
) -> dict[str, jnp.ndarray]:
    """Jit-cached wrapper that resolves closure_impl="auto" (env + backend)
    BEFORE entering jit, so the resolved impl is part of the static cache key
    — changing NEMO_CLOSURE_IMPL between calls takes effect instead of
    silently hitting the stale trace.

    pack_out=True replaces the bool summary outputs (and the diff tail's,
    when with_diff) with one bit-packed "packed_summary" uint8 vector
    (SUMMARY_PACK_LAYOUT / DIFF_PACK_LAYOUT) so a device behind an
    RPC-serialized tunnel ships one small transfer instead of many; the
    device-owning boundary unpacks (backend/jax_backend.py:_unpack_summary,
    service/server.py:_analyze_one).

    with_diff=False drops the differential-provenance tail (diff vs batch
    row 0) AND the num_labels dim from the compiled program — the
    production JaxBackend runs diff as its own good-run-anchored dispatch,
    and without the label vocab in the signature every corpus with the same
    (V, E, B, T, depth) buckets shares one compiled program.

    comp_linear=True (caller-VERIFIED via ops.simplify.chains_linear_host:
    every run's @next member subgraph is a linear chain — true for the
    `t(C+1)@next :- t(C)` persistence rules the domain generates) swaps the
    component-label all-pairs closures for O(V log V) pointer doubling,
    removing ~2/3 of the step's V^3 squaring work."""
    if closure_impl == "auto":
        from nemo_tpu.ops.adjacency import resolve_closure_impl

        closure_impl = resolve_closure_impl()
    return _analysis_step_jit(
        pre,
        post,
        v=v,
        pre_tid=pre_tid,
        post_tid=post_tid,
        num_tables=num_tables,
        num_labels=num_labels if with_diff else 1,
        max_depth=max_depth,
        closure_impl=closure_impl,
        with_diff=with_diff,
        comp_linear=comp_linear,
        pack_out=pack_out,
    )


def widen_batch(ba: BatchArrays) -> BatchArrays:
    """Cast narrow integer planes back to int32 INSIDE the compiled
    program.  The dispatch boundary may ship edge indices / table ids /
    type ids as int8/int16 (and an unused label plane as a [1,1] stub) to
    cut host->device upload bytes — on the TPU tunnel the upload of the
    packed planes is bandwidth-priced, so halving/quartering the bytes is
    wall time off the e2e critical path; the widening here costs one fused
    element-wise pass on device.  int32 callers are untouched (the cast is
    a no-op that XLA folds away; jit caches key on input dtypes, so each
    scheme compiles once)."""
    import dataclasses

    def w(a):
        return a.astype(jnp.int32) if a.dtype in (jnp.int8, jnp.int16) else a

    return dataclasses.replace(
        ba,
        edge_src=w(ba.edge_src),
        edge_dst=w(ba.edge_dst),
        table_id=w(ba.table_id),
        label_id=w(ba.label_id),
        type_id=w(ba.type_id),
    )


# pre_tid/post_tid are traced scalars, NOT statics: they only feed
# elementwise comparisons (ops/condition.py), and keeping them out of the
# cache key lets corpora with different vocab interning orders share one
# compiled program — fewer (slow) TPU compiles per multi-family sweep.
@partial(
    jax.jit,
    static_argnames=(
        "v",
        "num_tables",
        "num_labels",
        "max_depth",
        "closure_impl",
        "with_diff",
        "comp_linear",
        "pack_out",
    ),
)
def _analysis_step_jit(
    pre: BatchArrays,
    post: BatchArrays,
    v: int,
    pre_tid: int,
    post_tid: int,
    num_tables: int,
    num_labels: int,
    max_depth: int,
    closure_impl: str = "auto",
    with_diff: bool = True,
    comp_linear: bool = False,
    pack_out: bool = False,
) -> dict[str, jnp.ndarray]:
    """The full fused pipeline for one run batch.  Returns per-run and
    corpus-level results; everything stays on device."""
    pre = widen_batch(pre)
    post = widen_batch(post)
    adj_pre = build_adjacency(pre.edge_src, pre.edge_dst, pre.edge_mask, v)
    adj_post = build_adjacency(post.edge_src, post.edge_dst, post.edge_mask, v)

    # Condition marking (pre-post-prov.go:218-244).
    pre_holds = mark_condition_holds(
        adj_pre, pre.is_goal, pre.table_id, pre.node_mask, pre_tid, num_tables
    )
    post_holds = mark_condition_holds(
        adj_post, post.is_goal, post.table_id, post.node_mask, post_tid, num_tables
    )
    achieved_pre = pre_holds.any(axis=-1)

    # Simplification of both conditions (preprocessing.go:351-387).
    pre_clean, pre_alive = clean_masks(adj_pre, pre.is_goal, pre.node_mask)
    pre_adj2, pre_alive2, pre_type2 = collapse_chains(
        pre_clean, pre.is_goal, pre.type_id, pre_alive, closure_impl=closure_impl,
        comp_doubling=comp_linear,
    )
    post_clean, post_alive = clean_masks(adj_post, post.is_goal, post.node_mask)
    post_adj2, post_alive2, post_type2 = collapse_chains(
        post_clean, post.is_goal, post.type_id, post_alive, closure_impl=closure_impl,
        comp_doubling=comp_linear,
    )

    # Prototypes over the simplified consequent (prototype.go:11-130).
    bits, min_depth = proto_rule_bits(
        post_adj2,
        post.is_goal,
        post_alive2,
        post.table_id,
        achieved_pre,
        num_tables,
        max_depth,
        closure_impl=closure_impl,
    )
    present = all_rule_bits(post.is_goal, post_alive2, post.table_id, num_tables)
    inter, union = reduce_protos(bits, achieved_pre)

    out = {
        "pre_holds": pre_holds,
        "post_holds": post_holds,
        "achieved_pre": achieved_pre,
        "pre_adj_clean": pre_adj2,
        "pre_alive": pre_alive2,
        "pre_type": pre_type2,
        "post_adj_clean": post_adj2,
        "post_alive": post_alive2,
        "post_type": post_type2,
        "proto_bits": bits,
        "proto_min_depth": min_depth,
        "proto_present": present,
        "proto_inter": inter,
        "proto_union": union,
    }
    if with_diff:
        # Differential provenance of every run vs the successful run in row
        # 0 (differential-provenance.go:18-243).  Label bitsets per run.
        lid = jnp.clip(post.label_id, 0, num_labels - 1)
        sel = post.is_goal & post.node_mask & (post.label_id >= 0)
        run_bits = jnp.zeros((post.label_id.shape[0], num_labels), dtype=bool)
        run_bits = jax.vmap(lambda b, l, m: b.at[l].max(m))(run_bits, lid, sel)
        node_keep, edge_keep, frontier_rule, missing_goal = diff_masks(
            adj_post[0],
            post.is_goal[0],
            post.node_mask[0],
            post.label_id[0],
            run_bits,
            max_depth,
            closure_impl=closure_impl,
        )
        out["diff_node_keep"] = node_keep
        out["diff_frontier_rule"] = frontier_rule
        out["diff_missing_goal"] = missing_goal
    if pack_out:
        # Device->host copies over the TPU tunnel are RPC-serialized at
        # ~RTT each regardless of size (measured ~190 ms x ~8 summary
        # arrays per 17k-run bucket), so one 8x-smaller folded transfer
        # replaces them all.
        fold_packed_summary(
            out, SUMMARY_PACK_LAYOUT + (DIFF_PACK_LAYOUT if with_diff else ())
        )
    return out


def graphs_to_step(
    run_ids: list[int], pre_graphs: list, post_graphs: list, vocab: CorpusVocab
) -> tuple[BatchArrays, BatchArrays, dict]:
    """Common tail of every pack path: one shared (V, E) bucket over both
    conditions, two packed batches, and analysis_step's static kwargs."""
    from nemo_tpu.graphs.packed import bucket_size

    v = bucket_size(max(g.n_nodes for g in pre_graphs + post_graphs))
    e = bucket_size(max(max(len(g.edges) for g in pre_graphs + post_graphs), 1))
    pre_b = pack_batch(run_ids, pre_graphs, v, e)
    post_b = pack_batch(run_ids, post_graphs, v, e)
    # Static dims round up to powers of two so corpora with nearby vocab
    # sizes / diameters share one compiled program (vocab-dependent extra
    # table/label columns are never set, so results are unchanged;
    # max_depth only needs to be >= the true longest path).
    from nemo_tpu.ops.simplify import pair_chains_linear

    static = dict(
        v=v,
        pre_tid=vocab.tables.lookup("pre"),
        post_tid=vocab.tables.lookup("post"),
        num_tables=bucket_size(len(vocab.tables), 8),
        num_labels=bucket_size(max(1, len(vocab.labels)), 8),
        # Tight static trip count for the depth-relaxation loops: the corpus'
        # longest DAG path (+1 margin), not V — several-fold fewer sequential
        # steps on shallow provenance graphs (packed.py:longest_path_len).
        max_depth=bucket_size(max(pre_b.max_depth, post_b.max_depth), 4),
        # Host-verified linear-chain flag: selects the O(V log V)
        # component-label fast path in the step (exactness guaranteed by the
        # verification; False = assumption-free closure labels).  Computed
        # here so EVERY pack path — sidecar chunks included — carries the
        # deployment flag.
        comp_linear=pair_chains_linear(pre_b, post_b),
    )
    return BatchArrays.from_packed(pre_b), BatchArrays.from_packed(post_b), static


def pack_molly_for_step(
    molly: MollyOutput, vocab: CorpusVocab | None = None
) -> tuple[BatchArrays, BatchArrays, dict]:
    """Pack a whole corpus into one common-bucket pre batch + post batch,
    returning (pre, post, static_kwargs) ready for analysis_step."""
    vocab = vocab or CorpusVocab()
    run_ids = [r.iteration for r in molly.runs]
    pre_graphs = [pack_graph(r.pre_prov, vocab) for r in molly.runs]
    post_graphs = [pack_graph(r.post_prov, vocab) for r in molly.runs]
    return graphs_to_step(run_ids, pre_graphs, post_graphs, vocab)


def pack_corpus_for_step(corpus) -> tuple[BatchArrays, BatchArrays, dict]:
    """Packed-corpus bundle (graphs/corpus.py) -> step inputs, without
    touching the original Molly directory — the resume path: ingest once,
    save_corpus, then benchmark/analyze from the bundle alone."""
    run_ids = list(corpus.run_ids)
    pre_graphs = [corpus.graphs[(i, "pre")] for i in run_ids]
    post_graphs = [corpus.graphs[(i, "post")] for i in run_ids]
    return graphs_to_step(run_ids, pre_graphs, post_graphs, corpus.vocab)


def synth_batch_arrays(
    n_runs: int, seed: int = 0, eot: int = 6
) -> tuple[BatchArrays, BatchArrays, dict]:
    """Synthetic corpus -> step inputs, for benchmarks and compile checks."""
    import tempfile

    from nemo_tpu.ingest.molly import load_molly_output
    from nemo_tpu.models.synth import SynthSpec, write_corpus

    with tempfile.TemporaryDirectory() as d:
        corpus = write_corpus(SynthSpec(n_runs=n_runs, seed=seed, eot=eot), d)
        return pack_molly_for_step(load_molly_output(corpus))
