"""Shared scaffolding for on-demand-compiled C++ shared libraries.

Both native engines (ingestion, native/nemo_native.cpp via ingest/native.py;
figure rendering, native/nemo_report.cpp via report/native.py) follow the same
lifecycle: compile with g++ when missing or stale, load via ctypes, bind
symbols, check an ABI version, and degrade gracefully (Python fallback) when
the toolchain is absent.  That lifecycle lives here once.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable


def build_shared_lib(src: str, lib: str, force: bool = False) -> str:
    """Compile src -> lib if missing/stale; returns lib's absolute path.

    Builds to a temp name then renames: atomic under concurrent test workers.
    """
    src = os.path.abspath(src)
    lib = os.path.abspath(lib)
    if not os.path.exists(src):
        raise FileNotFoundError(src)
    if not force and os.path.exists(lib) and os.path.getmtime(lib) >= os.path.getmtime(src):
        return lib
    os.makedirs(os.path.dirname(lib), exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(lib))
    os.close(fd)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as ex:
        os.unlink(tmp)
        raise RuntimeError(f"native build failed: {ex.stderr}") from ex
    except OSError as ex:  # g++ missing entirely
        os.unlink(tmp)
        raise RuntimeError(f"native build failed: {ex}") from ex
    os.replace(tmp, lib)
    return lib


class NativeLib:
    """Lazy ctypes loader: build, bind, ABI-check once; cache lib or error."""

    def __init__(
        self,
        src: str,
        lib_path: str,
        bind: Callable[[ctypes.CDLL], None],
        abi_symbol: str,
        abi_version: int,
    ) -> None:
        self._src = src
        self._lib_path = lib_path
        self._bind = bind
        self._abi_symbol = abi_symbol
        self._abi_version = abi_version
        self._lib: ctypes.CDLL | None = None
        self._error: str | None = None

    def build(self, force: bool = False) -> str:
        return build_shared_lib(self._src, self._lib_path, force=force)

    def load(self) -> ctypes.CDLL | None:
        if self._lib is not None or self._error is not None:
            return self._lib
        try:
            path = self.build()
            lib = ctypes.CDLL(path)
            # ABI check and symbol binding stay inside the try: a stale .so
            # missing symbols must degrade to the Python fallback, not raise.
            abi = getattr(lib, self._abi_symbol)
            abi.restype = ctypes.c_int
            if abi() != self._abi_version:
                self._error = "ABI version mismatch"
                return None
            self._bind(lib)
        except Exception as ex:  # toolchain missing, build failure, ...
            self._error = str(ex)
            return None
        self._lib = lib
        return self._lib

    @property
    def available(self) -> bool:
        return self.load() is not None

    @property
    def error(self) -> str | None:
        self.load()
        return self._error
