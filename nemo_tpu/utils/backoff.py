"""The shared jittered-exponential-backoff policy (ISSUE 9 satellite).

Before this module, every retry loop hand-rolled its own waits: the RPC
client's UNAVAILABLE backoff (0.2 s doubling, no jitter), its
``nemo-retry-after-s`` throttle path (server hint clamped at 10 s, no
budget), and the scheduler's failover pause.  One policy now produces every
wait so the shapes cannot drift:

  * **jittered exponential**: attempt k sleeps ``base * multiplier**k``
    scaled by a uniform ``1 ± jitter`` factor — jitter is what keeps a herd
    of clients rejected together from re-arriving together;
  * **server hints win** (bounded): a ``retry-after`` hint from the server
    replaces the exponential term for that attempt (the server knows its
    own queue), clamped to ``max_delay`` so a wild hint cannot park the
    client;
  * **total budget**: cumulative sleep across one logical operation is
    capped (``budget_s``); past it the next ``delay()`` returns None and
    the caller gives up — bounded worst-case latency instead of "retries
    exhausted eventually".

Deterministic under test: pass ``rng`` (a ``random.Random``) to pin the
jitter.
"""

from __future__ import annotations

import random


class BackoffPolicy:
    """Stateless policy half: knows the shape of the waits."""

    def __init__(
        self,
        base_s: float = 0.2,
        multiplier: float = 2.0,
        max_delay_s: float = 10.0,
        jitter: float = 0.25,
        budget_s: float = 60.0,
    ) -> None:
        self.base_s = float(base_s)
        self.multiplier = float(multiplier)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.budget_s = float(budget_s)

    def session(self, rng: random.Random | None = None) -> "BackoffSession":
        return BackoffSession(self, rng)


class BackoffSession:
    """Stateful half: one logical operation's attempt counter and spent
    budget.  ``delay(hint_s=...)`` returns the next sleep in seconds, or
    None when the budget is exhausted (the caller should stop retrying and
    surface the last error)."""

    def __init__(self, policy: BackoffPolicy, rng: random.Random | None = None) -> None:
        self.policy = policy
        self.attempt = 0
        self.spent_s = 0.0
        self._rng = rng or random

    def delay(self, hint_s: float | None = None) -> float | None:
        p = self.policy
        if hint_s is not None and hint_s >= 0:
            raw = float(hint_s)
        else:
            raw = p.base_s * (p.multiplier ** self.attempt)
        raw = min(raw, p.max_delay_s)
        factor = 1.0 + p.jitter * (2.0 * self._rng.random() - 1.0)
        wait = max(0.0, raw * factor)
        if self.spent_s + wait > p.budget_s:
            return None
        self.attempt += 1
        self.spent_s += wait
        return wait


#: The RPC client's policy (service/client.py): the historic 0.2 s doubling
#: start, the historic 10 s throttle clamp, and a 60 s total budget — a
#: request that cannot land inside a minute of waiting should fail loudly,
#: not accumulate unbounded latency.
RPC_POLICY = BackoffPolicy(
    base_s=0.2, multiplier=2.0, max_delay_s=10.0, jitter=0.25, budget_s=60.0
)

#: The scheduler's lane-failover pause (parallel/sched.py): short — the
#: host lane is local and healthy, the pause only de-synchronizes a burst
#: of failing device jobs — with a tight budget so a drain never stalls.
FAILOVER_POLICY = BackoffPolicy(
    base_s=0.05, multiplier=2.0, max_delay_s=1.0, jitter=0.5, budget_s=5.0
)
