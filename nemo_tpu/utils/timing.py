"""Structured per-phase timing.

The reference's only observability is printf phase banners
(e.g. graphing/pre-post-prov.go:249); here every pipeline phase gets a wall
timer so the benchmark metrics (provenance-graphs/sec, per-phase p50) are
first-class (SURVEY.md §5 'Tracing / profiling').
"""

from __future__ import annotations

import contextlib
import time


class PhaseTimer:
    def __init__(self) -> None:
        self._timings: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._timings[name] = self._timings.get(name, 0.0) + time.perf_counter() - start

    def as_dict(self) -> dict[str, float]:
        return dict(self._timings)
