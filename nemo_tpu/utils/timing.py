"""Structured per-phase timing, span-backed.

The reference's only observability is printf phase banners
(e.g. graphing/pre-post-prov.go:249); here every pipeline phase gets a wall
timer so the benchmark metrics (provenance-graphs/sec, per-phase p50) are
first-class (SURVEY.md §5 'Tracing / profiling').

Since the obs subsystem landed, PhaseTimer is a thin adapter over span
tracing: each phase measures ONE interval and feeds the same numbers to
both the `timings` dict (the long-standing bench/CLI contract — name ->
accumulated seconds) and, when tracing is enabled, a ``phase:<name>`` span
in the trace file.  The dict is thereby *derived from* the spans — the two
can never disagree, which tests/test_obs.py pins (timings == span
durations exactly).
"""

from __future__ import annotations

import contextlib
import time

from nemo_tpu.obs import trace as _trace


class PhaseTimer:
    def __init__(self) -> None:
        self._timings: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        start_ns = time.perf_counter_ns()
        try:
            yield
        finally:
            dur_ns = time.perf_counter_ns() - start_ns
            # One measurement, two consumers: the span's microsecond duration
            # and the dict's float seconds derive from the SAME interval.
            _trace.add_span(f"phase:{name}", start_ns // 1000, dur_ns // 1000)
            self._timings[name] = self._timings.get(name, 0.0) + dur_ns / 1e9

    def as_dict(self) -> dict[str, float]:
        return dict(self._timings)
