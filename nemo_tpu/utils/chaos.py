"""Chaos fault injector (ISSUE 9 tentpole part 4).

Nemo's whole purpose is debugging distributed protocols under injected
faults; this module points the same discipline at Nemo itself.  Faults are
armed via the ``NEMO_CHAOS`` env — a ``;``-separated list of modes — and
fire at named injection points compiled into the production code paths.
With ``NEMO_CHAOS`` unset every hook is a single dict lookup on a None
module global (measured noise-level), so the hooks stay in the hot paths
permanently, exactly like the obs spans.

Modes (``name`` or ``name:arg``):

  ``fail_dispatch:N``        the first N device-lane kernel dispatches
                             raise :class:`ChaosFault` (an "XLA error" for
                             the scheduler's failover/breaker machinery)
  ``wedge_dispatch:N``       the first N device-lane dispatches SLEEP far
                             past any deadline (exercises
                             ``NEMO_DISPATCH_TIMEOUT_S`` abandonment)
  ``kill_after_segments:N``  SIGKILL this process right after the Nth
                             segment partial is published (crash-safe
                             resume scenario — no cleanup handlers run,
                             exactly like a real OOM kill)
  ``kill_in_store_publish``  SIGKILL mid store-segment write (the
                             store-writer crash-recovery scenario: tmp
                             wreckage + the fcntl lock are all that's left)
  ``slow_io:S``              sleep S seconds at the store/cache IO points

Counters are process-global and monotonic: ``fail_dispatch:2`` means "the
first 2 matching calls ever in this process", which is what makes the
injected schedule deterministic.  Helpers below (``corrupt_run_file``,
``corrupt_rcache_entry``) are for harnesses that corrupt state ON DISK
before a run, rather than injecting at a point in time.

Every fired injection logs a ``chaos.injected`` record and bumps a
``chaos.injected.<point>`` counter, so a chaos run's report/telemetry is
self-describing.
"""

from __future__ import annotations

import os
import signal
import threading
import time

from nemo_tpu import obs
from nemo_tpu.obs import log as obs_log

_log = obs_log.get_logger("nemo.chaos")


class ChaosFault(RuntimeError):
    """An injected fault.  Deliberately a RuntimeError: the scheduler's
    lane-failure classification must treat it like the real XLA/OOM errors
    it stands in for."""


_lock = threading.Lock()
#: mode -> remaining budget (int) or parameter (float); None = chaos off.
_spec: dict[str, float] | None = None
_spec_env: str | None = object()  # sentinel: not yet parsed


def _parse(env: str) -> dict[str, float]:
    spec: dict[str, float] = {}
    for part in env.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, arg = part.partition(":")
        name = name.strip().lower()
        try:
            val = float(arg) if arg else 1.0
        except ValueError:
            _log.warning("chaos.bad_mode", mode=part, detail="argument not a number")
            continue
        spec[name] = val
    return spec


def _active() -> dict[str, float] | None:
    """The parsed NEMO_CHAOS spec, re-parsed when the env changes (tests
    flip it per-case; production sets it once at launch)."""
    global _spec, _spec_env
    env = os.environ.get("NEMO_CHAOS") or None
    if env == _spec_env:
        return _spec
    with _lock:
        _spec_env = env
        _spec = _parse(env) if env else None
    return _spec


def reset() -> None:
    """Forget consumed budgets (tests)."""
    global _spec, _spec_env
    with _lock:
        _spec = None
        _spec_env = object()


def _consume(spec: dict, mode: str) -> bool:
    """Atomically take one unit of a counted mode's budget."""
    with _lock:
        left = spec.get(mode, 0)
        if left <= 0:
            return False
        spec[mode] = left - 1
    return True


def _fired(point: str, **ctx) -> None:
    obs.metrics.inc(f"chaos.injected.{point}")
    _log.warning("chaos.injected", point=point, **ctx)


# ---------------------------------------------------------------------------
# injection points
# ---------------------------------------------------------------------------


def on_device_dispatch(verb: str) -> None:
    """Hook at the top of every device-lane kernel dispatch
    (backend/jax_backend.py:LocalExecutor.run).  May raise ChaosFault
    (``fail_dispatch``) or sleep past any deadline (``wedge_dispatch``)."""
    spec = _active()
    if not spec:
        return
    if "fail_dispatch" in spec and _consume(spec, "fail_dispatch"):
        _fired("fail_dispatch", verb=verb)
        raise ChaosFault(f"injected device dispatch failure (verb={verb})")
    if "wedge_dispatch" in spec and _consume(spec, "wedge_dispatch"):
        _fired("wedge_dispatch", verb=verb)
        # Far past any sane NEMO_DISPATCH_TIMEOUT_S; the abandoning
        # scheduler leaves this thread behind as a daemon.
        time.sleep(3600.0)


def on_segment_published(n_published: int) -> None:
    """Hook after the pipeline publishes one segment partial
    (analysis/pipeline.py checkpoint loop).  ``kill_after_segments:N``
    SIGKILLs the process once N partials are on disk — no atexit, no
    finally blocks, the honest crash."""
    spec = _active()
    if not spec:
        return
    n = spec.get("kill_after_segments")
    if n is not None and n_published >= n:
        _fired("kill_after_segments", published=n_published)
        os.kill(os.getpid(), signal.SIGKILL)


def on_store_publish() -> None:
    """Hook inside the store's populate, after shard bytes are written but
    BEFORE the atomic rename publishes them (store/__init__.py:_put)."""
    spec = _active()
    if not spec:
        return
    if "kill_in_store_publish" in spec and _consume(spec, "kill_in_store_publish"):
        _fired("kill_in_store_publish")
        os.kill(os.getpid(), signal.SIGKILL)


def on_slow_io(point: str) -> None:
    """Hook at store/cache IO boundaries: ``slow_io:S`` sleeps S seconds,
    modeling a degraded network filesystem."""
    spec = _active()
    if not spec:
        return
    s = spec.get("slow_io")
    if s:
        _fired("slow_io", point=point, seconds=s)
        time.sleep(s)


# ---------------------------------------------------------------------------
# on-disk corruption helpers (used by harnesses, not injection points)
# ---------------------------------------------------------------------------


def corrupt_run_file(corpus_dir: str, position: int, kind: str = "truncate") -> str:
    """Corrupt one run's post-provenance JSON in place; returns the file
    name.  ``truncate`` cuts the file mid-token; ``garbage`` replaces it
    with non-JSON bytes — both are quarantine-class parse failures."""
    name = f"run_{position}_post_provenance.json"
    path = os.path.join(corpus_dir, name)
    with open(path, "rb") as fh:
        data = fh.read()
    with open(path, "wb") as fh:
        if kind == "garbage":
            fh.write(b"\xff\xfenot json{{{")
        else:
            fh.write(data[: max(1, len(data) // 2)])
    return name


def corrupt_rcache_entry(cache_root: str, kind: str = "partial") -> str | None:
    """Flip bytes in the first ``<kind>/`` entry's payload under a result
    cache root; returns the entry dir or None when none exists.  The next
    load must fail the manifest verify and recompute loudly."""
    kdir = os.path.join(cache_root, kind)
    try:
        entries = sorted(
            d for d in os.listdir(kdir) if ".tmp-" not in d
        )
    except OSError:
        return None
    if not entries:
        return None
    d = os.path.join(kdir, entries[0])
    for dirpath, _, files in os.walk(d):
        for f in files:
            if f == "entry.json":
                continue
            p = os.path.join(dirpath, f)
            with open(p, "r+b") as fh:
                fh.seek(0)
                first = fh.read(1)
                fh.seek(0)
                fh.write(bytes([first[0] ^ 0xFF]) if first else b"\xff")
            return d
    # Entry with no payload files: corrupt the entry.json itself.
    with open(os.path.join(d, "entry.json"), "ab") as fh:
        fh.write(b"garbage")
    return d
