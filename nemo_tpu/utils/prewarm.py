"""Prewarm the persistent compilation cache with the stress-floor programs.

`python -m nemo_tpu.utils.prewarm` (or `make prewarm`) compiles each
case-study family's fused analysis_step at the stress-scale bucket signature
— the same jit cache key the CLI pipeline, the sidecar, and the benchmark
dispatch through (backend/jax_backend.py:_k_fused resolves to the identical
analysis_step computation) — so a first stress run pays disk-cache loads
instead of fresh compiles (VERDICT r3 task 4).

The signature's corpus-dependent statics (table ids, table-count bucket,
max-depth bucket) are derived from a SMALL generated corpus of the same
family: the case-study generators draw every run from a fixed protocol
template, so vocab order and depth bounds are corpus-size-independent
(verified by the packed-ingest parity suite at multiple sizes).  Batch-axis
dims are shape floors: runs-per-family pads to the power-of-two run bucket,
V/E/table floors are the >=512-run stress floors of the fused dispatch.

Out of scope (documented, not compiled): the dense diff program — its
failed-run pad and label-vocab bucket depend on corpus content at full
scale, and small jobs route to the host path anyway — and the giant-run
program (own shape family).
"""

from __future__ import annotations

import argparse
import sys
import tempfile
import time


def stress_signature(name: str, n_probe: int, b_pad: int):
    """The exact (pre, post, static) the stress-scale fused dispatch uses
    for this family: probe-corpus statics under the backend's big-corpus
    floors (tests/test_compile_sharing.py:test_prewarm_matches_deployment
    pins this derivation to the real dispatch signature)."""
    import numpy as np

    from nemo_tpu.graphs.packed import bucket_size
    from nemo_tpu.ingest.native import native_available, pack_molly_dir
    from nemo_tpu.models.case_studies import write_case_study
    from nemo_tpu.models.pipeline_model import BatchArrays, pack_molly_for_step

    if b_pad < n_probe:
        raise ValueError(
            f"run-axis pad {b_pad} smaller than the probe corpus ({n_probe} runs); "
            "raise --runs-per-family or lower --probe-runs"
        )
    with tempfile.TemporaryDirectory(prefix="nemo_prewarm_") as tmp:
        d = write_case_study(name, n_runs=n_probe, seed=11, out_dir=tmp)
        if native_available():
            pre, post, static = pack_molly_dir(d)
        else:
            from nemo_tpu.ingest.molly import load_molly_output

            pre, post, static = pack_molly_for_step(load_molly_output(d))

    # Stress-floor statics of the fused dispatch (backend/jax_backend.py
    # _fused, big-corpus branch): V/E floors 64/256, table bucket floor 32,
    # labels pinned to 8 (no diff tail), depth bucket floor 32, run axis
    # padded to b_pad, and the linearity flag the deployment's host check
    # would set for this family.
    # comp_linear arrives in `static` from the pack path itself
    # (graphs_to_step / pack_molly_dir) — the same reduction the deployment
    # dispatch uses.
    v = max(64, static["v"])
    e = max(256, int(pre.edge_src.shape[1]))
    static = dict(
        static,
        v=v,
        num_tables=bucket_size(static["num_tables"], 32),
        num_labels=8,
        max_depth=bucket_size(static["max_depth"], 32),
    )
    static["with_diff"] = 0
    # Match the executor's transfer-packing choice for THIS backend, or the
    # prewarmed program won't be the one the deployment dispatches
    # (backend/jax_backend.py:_pack_out_default).
    from nemo_tpu.backend.jax_backend import _pack_out_default

    static["pack_out"] = bool(_pack_out_default())

    def pad_arrays(ba: BatchArrays) -> BatchArrays:
        def grow(a, cols, fill):
            out = np.full((b_pad, cols), fill, dtype=np.asarray(a).dtype)
            src = np.asarray(a)
            out[: src.shape[0], : src.shape[1]] = src[:, : min(cols, src.shape[1])]
            return out

        return BatchArrays(
            edge_src=grow(ba.edge_src, e, 0),
            edge_dst=grow(ba.edge_dst, e, 0),
            edge_mask=grow(ba.edge_mask, e, False),
            is_goal=grow(ba.is_goal, v, False),
            table_id=grow(ba.table_id, v, -1),
            label_id=grow(ba.label_id, v, -1),
            type_id=grow(ba.type_id, v, 0),
            node_mask=grow(ba.node_mask, v, False),
        )

    # The deployment dispatch narrows the upload dtypes and stubs the
    # unused label plane (backend/jax_backend.py:_narrow_fused_arrays);
    # dtype and shape are both part of the jit signature, so prewarm must
    # mirror them or it compiles a program nobody runs.  The default
    # resolution here (local platform) matches both deployments: the
    # in-process backend resolves the same default from the same process,
    # and RemoteExecutor clients now narrow unconditionally (ADVICE r5 #1,
    # ServiceBackend._resolve_narrow_xfer) — matching a prewarm run on the
    # device-owning sidecar, whose platform resolves narrowing ON.
    from dataclasses import replace

    from nemo_tpu.backend.jax_backend import _narrow_fused_arrays

    pre_p, post_p = pad_arrays(pre), pad_arrays(post)
    arrays = _narrow_fused_arrays(
        {f"pre_{f}": getattr(pre_p, f) for f in BatchArrays.FIELDS}
        | {f"post_{f}": getattr(post_p, f) for f in BatchArrays.FIELDS},
        v=v,
        num_tables=static["num_tables"],
        with_diff=False,
    )
    pre_p = replace(pre_p, **{f: arrays[f"pre_{f}"] for f in BatchArrays.FIELDS})
    post_p = replace(post_p, **{f: arrays[f"post_{f}"] for f in BatchArrays.FIELDS})
    return pre_p, post_p, static


def chunk_signature(name: str, n_probe: int, chunk_runs: int):
    """The sidecar's streamed-chunk dispatch signature for this family:
    every pipelined chunk (service/client.py:_uniform_spans) is exactly
    chunk_runs rows with the corpus statics passed VERBATIM (the server
    applies no floors — server.py:_analyze_one), and the family
    generators' statics are corpus-size-independent (same template per
    run), so a probe corpus padded on the run axis reproduces the exact
    jit cache key the first streamed chunk would compile."""
    import numpy as np

    from nemo_tpu.ingest.native import native_available, pack_molly_dir
    from nemo_tpu.models.case_studies import write_case_study
    from nemo_tpu.models.pipeline_model import BatchArrays, pack_molly_for_step

    with tempfile.TemporaryDirectory(prefix="nemo_prewarm_") as tmp:
        d = write_case_study(name, n_runs=n_probe, seed=11, out_dir=tmp)
        if native_available():
            pre, post, static = pack_molly_dir(d)
        else:
            from nemo_tpu.ingest.molly import load_molly_output

            pre, post, static = pack_molly_for_step(load_molly_output(d))

    def pad_rows(ba: BatchArrays) -> BatchArrays:
        def grow(a):
            a = np.asarray(a)[:chunk_runs]
            if a.shape[0] < chunk_runs:
                a = np.concatenate(
                    [a, np.repeat(a[:1], chunk_runs - a.shape[0], axis=0)]
                )
            return a

        return BatchArrays(**{f: grow(getattr(ba, f)) for f in BatchArrays.FIELDS})

    # The server injects its transfer-packing choice before dispatch
    # (server.py:_analyze_one); mirror it or the prewarmed chunk program
    # isn't the one the stream compiles.
    from nemo_tpu.backend.jax_backend import _pack_out_default

    static = dict(static, pack_out=bool(_pack_out_default()))
    return pad_rows(pre), pad_rows(post), static


def prewarm_family(
    name: str,
    n_probe: int,
    b_pad: int,
    chunk_runs: int = 0,
    include_stress: bool = True,
) -> float:
    """Compile (or disk-cache-load) this family's programs.  A serving
    replica's warm boot (service/server.py:_prewarm_async, ISSUE 14) sets
    ``include_stress=False`` to warm only the streamed-chunk signature —
    the shape every pipelined client dispatches — without paying the
    stress-floor compile at boot."""
    import jax

    from nemo_tpu.models.pipeline_model import analysis_step

    signatures = []
    if include_stress:
        signatures.append(stress_signature(name, n_probe, b_pad))
    if chunk_runs:
        signatures.append(chunk_signature(name, n_probe, chunk_runs))
    # Time ONLY compile+execute: operators read a near-zero per-family
    # number as "cache already hot", so corpus generation/packing I/O
    # must stay outside the window.
    t0 = time.perf_counter()
    for pre, post, static in signatures:
        out = analysis_step(pre, post, **static)
        jax.block_until_ready(out)
    return time.perf_counter() - t0


def main(argv: list[str] | None = None) -> int:
    from nemo_tpu.models.case_studies import CASE_STUDIES
    from nemo_tpu.utils.jax_config import enable_compilation_cache, ensure_platform

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--runs-per-family",
        type=int,
        default=1700,
        help="target stress scale; the run axis pads to its power-of-two "
        "bucket (default 1700 -> 2048, the 10,200-run bench shape)",
    )
    p.add_argument(
        "--probe-runs",
        type=int,
        default=64,
        help="small corpus size used to derive each family's statics",
    )
    p.add_argument(
        "--chunk-runs",
        type=int,
        default=512,
        help="also compile the sidecar's uniform streamed-chunk signature "
        "at this batch size (the analyze_dir_pipelined default); 0 disables",
    )
    p.add_argument("--platform", default=None)
    args = p.parse_args(argv)

    platform = ensure_platform(args.platform)
    print(f"jax platform: {platform}", file=sys.stderr)
    enable_compilation_cache()

    from nemo_tpu.graphs.packed import bucket_size

    b_pad = bucket_size(args.runs_per_family, 8)
    total = 0.0
    for name in sorted(CASE_STUDIES):
        dt = prewarm_family(name, args.probe_runs, b_pad, args.chunk_runs)
        total += dt
        print(
            f"  {name}: compiled+ran in {dt:.1f}s "
            f"(B={b_pad}, chunk B={args.chunk_runs or 'off'})",
            file=sys.stderr,
        )
    print(f"prewarm done in {total:.1f}s; persistent cache is hot", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
