"""Shared utility helpers."""

from __future__ import annotations

import os


def effective_cpu_count() -> int:
    """Cores THIS process may actually run on: the scheduling affinity set
    when the platform exposes it (containers/cgroups pin processes to a
    subset of os.cpu_count()), else os.cpu_count().

    This is the overlap-machinery gate (ISSUE 3 satellite): on a 1-core
    host a producer/prefetch thread cannot overlap with the consumer — the
    GIL handoffs and queue traffic are pure overhead, and benched
    "overlap" rows came out negative (BENCH_r05 single_dir_overlap:
    overlap_win_s -0.03 on the 1-core bench host) — so run_debug_dirs and
    the pipelined sidecar clients skip the producer thread entirely below
    2 cores (and say so, instead of shipping a negative win).
    """
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):  # non-Linux platforms
        return os.cpu_count() or 1
