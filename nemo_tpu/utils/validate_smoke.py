"""`make validate` tail: a CLI-shaped smoke on a synthetic corpus with the
jax backend's report byte-compared against the Python oracle's.

Covers the figure-render pipeline end to end (report/render.py) with an
all-figures smoke: the production report renders every figure
(figures="all") through the deduplicated / cached / parallel scheduler and
must be byte-identical — every .dot, every .svg, debugging.json — to the
same backend rendering sequentially (explicit Reporter, no scheduler: the
oracle render path).  A second pass must then serve every unique figure
from the persistent SVG cache (zero renders) and still match.  Backend
analysis parity stays what it was: the jax debugging.json equals the
Python oracle backend's (figure node ORDER differs across backends by
construction, so figure files are only byte-compared within one backend).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _tree(root: str) -> dict[str, bytes]:
    out: dict[str, bytes] = {}
    for dirpath, _, files in os.walk(root):
        for f in files:
            p = os.path.join(dirpath, f)
            with open(p, "rb") as fh:
                out[os.path.relpath(p, root)] = fh.read()
    return out


def main() -> int:
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.backend.python_ref import PythonBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus
    from nemo_tpu.report.writer import Reporter
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")  # never touch a (possibly tunneled) device here
    with tempfile.TemporaryDirectory(prefix="nemo_validate_") as tmp:
        # Hermetic SVG cache: cold for the first pass, warm for the second,
        # never the user's ~/.cache.
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        os.environ.pop("NEMO_RENDER_WORKERS", None)
        corpus = write_corpus(SynthSpec(n_runs=6, seed=3), tmp)

        # 1. Render-pipeline parity: pipeline (dedup+cache+workers) vs the
        # sequential per-figure oracle, same backend, full figure set.
        jx = run_debug(corpus, os.path.join(tmp, "jx"), JaxBackend(), figures="all")
        seq = run_debug(
            corpus,
            os.path.join(tmp, "seq"),
            JaxBackend(),
            reporter=Reporter(),  # no scheduler: the sequential oracle path
            figures="all",
        )
        a, b = _tree(jx.report_dir), _tree(seq.report_dir)
        if a.keys() != b.keys():
            print(
                "validate: report file sets DIVERGE: "
                f"{sorted(a.keys() ^ b.keys())[:10]}",
                file=sys.stderr,
            )
            return 1
        bad = sorted(k for k in a if a[k] != b[k])
        if bad:
            print(
                "validate: pipeline-rendered report DIVERGES from the "
                f"sequential renderer in {len(bad)} file(s), e.g. {bad[:5]}",
                file=sys.stderr,
            )
            return 1

        # 2. Cache-warm re-report: zero renders, identical bytes.
        jx2 = run_debug(corpus, os.path.join(tmp, "jx2"), JaxBackend(), figures="all")
        s = jx2.figure_stats or {}
        if s.get("rendered") != 0 or s.get("figure_cache_hits") != s.get("unique_figures"):
            print(f"validate: SVG cache not warm on the second pass: {s}", file=sys.stderr)
            return 1
        warm = _tree(jx2.report_dir)
        bad2 = sorted(k for k in a if warm.get(k) != a[k])
        if bad2:
            print(
                f"validate: cache-warm report DIVERGES in {len(bad2)} file(s), "
                f"e.g. {bad2[:5]}",
                file=sys.stderr,
            )
            return 1

        # 3. Backend analysis parity: jax debugging.json == oracle's.
        py = run_debug(
            corpus, os.path.join(tmp, "py"), PythonBackend(), figures="none"
        )
        with open(os.path.join(jx.report_dir, "debugging.json")) as f:
            dbg_jx = json.load(f)
        with open(os.path.join(py.report_dir, "debugging.json")) as f:
            dbg_py = json.load(f)
        if dbg_jx != dbg_py:
            print("validate: jax report DIVERGES from the oracle", file=sys.stderr)
            return 1

        n_figs = len([f for f in a if f.startswith("figures")])
        fs = jx.figure_stats or {}
        print(
            "validate: ok — oracle-identical report "
            f"({len(a)} files, {n_figs} figure files, dedup {fs.get('dedup_ratio')}x, "
            "sequential-parity + cache-warm re-report identical)"
        )
        return 0


if __name__ == "__main__":
    sys.exit(main())
