"""`make validate` tail: a CLI-shaped smoke on a synthetic corpus with the
jax backend's report byte-compared against the Python oracle's."""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.backend.python_ref import PythonBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")  # never touch a (possibly tunneled) device here
    with tempfile.TemporaryDirectory(prefix="nemo_validate_") as tmp:
        corpus = write_corpus(SynthSpec(n_runs=6, seed=3), tmp)
        jx = run_debug(corpus, os.path.join(tmp, "jx"), JaxBackend())
        py = run_debug(corpus, os.path.join(tmp, "py"), PythonBackend())
        with open(os.path.join(jx.report_dir, "debugging.json")) as f:
            a = json.load(f)
        with open(os.path.join(py.report_dir, "debugging.json")) as f:
            b = json.load(f)
        if a != b:
            print("validate: jax report DIVERGES from the oracle", file=sys.stderr)
            return 1
        n_figs = len(os.listdir(os.path.join(jx.report_dir, "figures")))
        print(f"validate: ok — oracle-identical report, {n_figs} figures")
        return 0


if __name__ == "__main__":
    sys.exit(main())
